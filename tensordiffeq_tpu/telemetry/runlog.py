"""JSONL event sink + run manifest + the one leveled narration path.

A run directory holds two files:

* ``manifest.json`` — schema version, run id, creation time, the run
  config the caller registered, and the environment (backend, devices);
  finalized on :meth:`RunLogger.close` with the end time and a metrics
  snapshot from the attached registry.
* ``events.jsonl`` — append-only, one schema-versioned JSON record per
  line: ``{"v": 2, "t": <unix s>, "kind": "...", ...}``.  Appends are
  flushed per event, so a killed run keeps everything up to the kill.

:func:`log_event` is the single narration path the package routes its
former bare ``print()`` lines through: a message prints only when the
caller's ``verbose`` flag says so (quiet runs are actually quiet), but the
event is *always* appended to the active :class:`RunLogger` when one is
attached — machine-readable even when silent.  Warnings and errors print
to stderr so bench workers' JSON-line stdout protocol stays clean.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import sys
import time
from typing import Any, Optional

import numpy as np

from .registry import MetricsRegistry, default_registry

# v2 (PR 7): adds the `trace` event kind (span records — trace/span/
# parent ids, start, dur_s, status, attrs) and the `step_cost` event.
# Reads stay back-compatible: neither read_events nor any consumer
# filters on `v`, so v1 logs parse, summarize, and report unchanged —
# they simply contain no spans.
SCHEMA_VERSION = 2
EVENTS_FILE = "events.jsonl"
MANIFEST_FILE = "manifest.json"

# stack, not a single slot: nested runs (a solver fit inside a bench
# harness that keeps its own log) resolve to the innermost logger
_ACTIVE: list = []

# event taps: callables fed every record any RunLogger appends, AFTER the
# disk write.  The flight recorder's ring rides this hook; taps must
# never raise into the logging path and are called best-effort.
_TAPS: list = []


def active_logger() -> Optional["RunLogger"]:
    """The innermost attached :class:`RunLogger`, or None."""
    return _ACTIVE[-1] if _ACTIVE else None


def _json_default(obj: Any):
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return str(obj)


# spec-valid JSON has no NaN/Infinity tokens; divergence records are exactly
# where they appear, and a strict consumer (jq, a dashboard ingester) must
# be able to parse exactly those lines — encode them as strings instead
NONFINITE_TOKENS = {"NaN", "Infinity", "-Infinity"}


def _sanitize(v):
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, float) and not math.isfinite(v):
        return "NaN" if math.isnan(v) else (
            "Infinity" if v > 0 else "-Infinity")
    if isinstance(v, np.ndarray):
        return _sanitize(v.tolist())
    if isinstance(v, dict):
        return {k: _sanitize(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_sanitize(x) for x in v]
    return v


def log_event(kind: str, message: Optional[str] = None, *,
              level: str = "info", verbose: bool = True,
              prefix: bool = True, logger: Optional["RunLogger"] = None,
              **fields):
    """Narrate + record in one call.

    ``message`` prints as ``[kind] message`` iff ``verbose`` (callers pass
    their existing ``verbose`` flags through); ``level`` in
    ``("warning", "error")`` prints to stderr, everything else to stdout.
    ``prefix=False`` prints the message bare (banners).  Independently of
    printing, the event — kind, level, message, and any extra ``fields``
    — is appended to ``logger`` (default: the active run logger) when one
    exists, so a quiet run still leaves a machine-readable trail.
    """
    if verbose and message is not None:
        stream = sys.stderr if level in ("warning", "error") else sys.stdout
        print(f"[{kind}] {message}" if prefix else message,
              file=stream, flush=True)
    lg = logger if logger is not None else active_logger()
    if lg is not None:
        rec = dict(fields)
        if message is not None:
            rec["message"] = message
        if level != "info":
            rec["level"] = level
        lg.event(kind, **rec)


class RunLogger:
    """Schema-versioned JSONL event sink for one run.

    Usage::

        with telemetry.RunLogger("runs/ac_sa_0", config={...}) as run:
            solver.fit(tf_iter=10_000, telemetry=run)
        print(telemetry.report("runs/ac_sa_0"))

    As a context manager the logger also becomes the *active* sink for
    :func:`log_event`, so package narration ([fit]/[autotune]/[causal]
    lines) lands in ``events.jsonl`` alongside the structured training
    events.  ``registry`` defaults to the process-wide
    :func:`~tensordiffeq_tpu.telemetry.default_registry` so serving/bench
    metrics snapshot into the manifest on close.
    """

    def __init__(self, run_dir: str, config: Optional[dict] = None,
                 registry: Optional[MetricsRegistry] = None,
                 run_id: Optional[str] = None, clock=time.time,
                 rotate_bytes: Optional[int] = None):
        self.run_dir = str(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.registry = registry if registry is not None else default_registry()
        self._clock = clock
        self.run_id = run_id or f"run-{os.getpid()}-{int(clock() * 1e3):x}"
        self.n_events = 0
        self._closed = False
        # size-based rotation: when the live file crosses the cap it is
        # renamed to the next `events.jsonl.<n>` segment (``.1`` oldest)
        # and a fresh live file opened.  Rotated segments are final —
        # never renamed again — so a collector tailing by (segment,
        # offset) keeps valid offsets across rotations.
        self.rotate_bytes = int(rotate_bytes) if rotate_bytes else None
        self.n_rotations = 0
        self._manifest = {
            "schema_version": SCHEMA_VERSION,
            "run_id": self.run_id,
            "created": self._clock(),
            "config": dict(config or {}),
            "environment": self._environment(),
        }
        self._write_manifest()
        self._fh = open(os.path.join(self.run_dir, EVENTS_FILE), "a")

    @staticmethod
    def _environment() -> dict:
        try:
            import jax
            devs = jax.devices()
            return {"backend": jax.default_backend(),
                    "device_count": len(devs),
                    "device_kind": devs[0].device_kind,
                    "jax_version": jax.__version__}
        except Exception as e:  # never let env introspection kill a run
            return {"error": f"{type(e).__name__}: {e}"}

    def _write_manifest(self):
        path = os.path.join(self.run_dir, MANIFEST_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(_sanitize(self._manifest), fh, indent=1,
                      allow_nan=False, default=_json_default)
        os.replace(tmp, path)

    # ------------------------------------------------------------------ #
    def event(self, kind: str, **fields):
        """Append one schema-versioned record; flushed immediately so a
        killed process loses nothing already logged."""
        if self._closed:
            raise ValueError(f"RunLogger for {self.run_dir} is closed")
        rec = {"v": SCHEMA_VERSION, "t": round(self._clock(), 6),
               "kind": str(kind)}
        rec.update(fields)
        rec = _sanitize(rec)
        self._fh.write(json.dumps(rec, allow_nan=False,
                                  default=_json_default) + "\n")
        self._fh.flush()
        self.n_events += 1
        if self.rotate_bytes is not None \
                and self._fh.tell() >= self.rotate_bytes:
            self._rotate()
        if _TAPS:  # flight recorders ride every append, best-effort
            for tap in list(_TAPS):
                try:
                    tap(rec)
                except Exception:
                    pass

    def _rotate(self):
        """Seal the live file as the next ``events.jsonl.<n>`` segment
        and open a fresh one.  Suffixes only ever grow (``.1`` is the
        oldest), so sealed segments stay byte-stable for tailing
        readers."""
        self._fh.close()
        nxt = 1
        for p in event_segments(self.run_dir)[:-1]:
            suf = p.rsplit(".", 1)[-1]
            if suf.isdigit():
                nxt = max(nxt, int(suf) + 1)
        os.replace(os.path.join(self.run_dir, EVENTS_FILE),
                   os.path.join(self.run_dir, f"{EVENTS_FILE}.{nxt}"))
        self._fh = open(os.path.join(self.run_dir, EVENTS_FILE), "a")
        self.n_rotations += 1

    def close(self):
        """Finalize: flush the sink and rewrite the manifest with the end
        time, event count, and a metrics snapshot."""
        if self._closed:
            return
        self._closed = True
        self._fh.close()
        self._manifest["ended"] = self._clock()
        self._manifest["n_events"] = self.n_events
        if self.n_rotations:
            self._manifest["n_rotations"] = self.n_rotations
        try:
            self._manifest["metrics"] = self.registry.as_dict()
        except Exception:
            pass
        self._write_manifest()
        with contextlib.suppress(ValueError):
            _ACTIVE.remove(self)

    # ------------------------------------------------------------------ #
    def __enter__(self) -> "RunLogger":
        _ACTIVE.append(self)
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def read_manifest(run_dir: str) -> dict:
    with open(os.path.join(run_dir, MANIFEST_FILE)) as fh:
        return json.load(fh)


def event_segments(run_dir: str) -> list:
    """The run's event files in append order: rotated segments
    (``events.jsonl.1`` oldest → highest suffix newest), then the live
    ``events.jsonl``.  Every multi-segment reader — :func:`read_events`,
    the collector's tails — iterates this."""
    run_dir = str(run_dir)
    base = os.path.join(run_dir, EVENTS_FILE)
    rotated = []
    try:
        names = os.listdir(run_dir)
    except OSError:
        names = []
    for n in names:
        if n.startswith(EVENTS_FILE + "."):
            suf = n[len(EVENTS_FILE) + 1:]
            if suf.isdigit():
                rotated.append((int(suf), os.path.join(run_dir, n)))
    segs = [p for _, p in sorted(rotated)]
    if os.path.exists(base):
        segs.append(base)
    return segs


def read_events(run_dir: str, kind: Optional[str] = None) -> list:
    """Parse the run's events back into dicts (optionally one ``kind``),
    reading seamlessly across rotated segments.  A truncated final line
    (process killed mid-write) is skipped per segment, not fatal — same
    salvage stance as ``bench.last_json_line``."""
    out = []
    for path in event_segments(run_dir):
        try:
            fh = open(path)
        except OSError:
            continue
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if kind is None or rec.get("kind") == kind:
                    out.append(rec)
    return out
