"""Service-level objectives: declared targets, machine-checkable verdicts.

The registry and run log record what happened; this module says whether
it was *acceptable*.  An :class:`SLOSet` declares the fleet's objectives
— serving p99 latency, shed (rejected) fraction, timed-out fraction,
training step-time regression against the run's own baseline — and
evaluates them against live registry state (:meth:`SLOSet.evaluate`) or
a finished/killed run directory (:meth:`SLOSet.evaluate_run`).  Every
objective reports a burn rate (observed value over budget, the
burn-rate-window idiom: >1 means the error budget is being spent faster
than allowed; the step-regression objective compares a trailing window
against the run's opening baseline window rather than a global mean, so
a late regression is not averaged away).

The verdict is plain JSON (``{"ok": bool, "objectives": {...},
"breaches": [...]}``) consumed by three front-ends:
:meth:`~tensordiffeq_tpu.fleet.FleetRouter.autoscale_signals` (scale-up
on burn), ``telemetry.report`` (the SLO block in the human diagnosis),
and ``bench.py --slo`` (CI gate: nonzero exit on breach).

:func:`to_prometheus` renders any registry (or its ``as_dict()``) in
Prometheus text exposition format — a pure formatter, no server: dump it
behind any HTTP handler or into a textfile-collector drop and the
existing dashboards scrape it.
"""

from __future__ import annotations

import re
from typing import Optional

from .runlog import read_events, read_manifest

_KEY_RE = re.compile(r"^(?P<name>[^{]+)(\{(?P<labels>.*)\})?$", re.DOTALL)


def _parse_key(key: str):
    """Split a registry key ``name{a=b,c=d}`` into (name, {labels})."""
    m = _KEY_RE.match(key)
    if m is None:
        return key, {}
    labels = {}
    for part in (m.group("labels") or "").split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            labels[k] = v
    return m.group("name"), labels


def _sum_counters(metrics: dict, base: str) -> float:
    """Sum every labeled instance of counter ``base`` in an ``as_dict()``
    snapshot."""
    total = 0.0
    for key, v in (metrics.get("counters") or {}).items():
        if _parse_key(key)[0] == base and isinstance(v, (int, float)):
            total += v
    return total


def _max_hist_p99(metrics: dict, base: str) -> Optional[float]:
    """Worst p99 across every labeled instance of histogram ``base``."""
    worst = None
    for key, summ in (metrics.get("histograms") or {}).items():
        if _parse_key(key)[0] != base or not isinstance(summ, dict):
            continue
        p99 = summ.get("p99")
        if isinstance(p99, (int, float)):
            worst = p99 if worst is None else max(worst, p99)
    return worst


def _max_gauge(metrics: dict, base: str) -> Optional[float]:
    """Worst value across every labeled instance of gauge ``base``."""
    worst = None
    for key, v in (metrics.get("gauges") or {}).items():
        if _parse_key(key)[0] != base or not isinstance(v, (int, float)):
            continue
        worst = v if worst is None else max(worst, v)
    return worst


def _min_gauge(metrics: dict, base: str) -> Optional[float]:
    """Worst value across every labeled instance of a HIGHER-IS-BETTER
    gauge ``base`` (availability-style: the minimum is the worst)."""
    worst = None
    for key, v in (metrics.get("gauges") or {}).items():
        if _parse_key(key)[0] != base or not isinstance(v, (int, float)):
            continue
        worst = v if worst is None else min(worst, v)
    return worst


def _objective(value, threshold) -> dict:
    """One objective's verdict row.  ``ok`` is None when there is no
    data — absence of traffic is not a breach."""
    ok = None if value is None else bool(value <= threshold)
    burn = (None if value is None or threshold <= 0
            else round(value / threshold, 4))
    return {"value": value, "threshold": threshold, "ok": ok,
            "burn_rate": burn}


class SLOSet:
    """Declared objectives + their evaluation (see module docstring).

    Args:
      serving_p99_s: worst acceptable per-request p99 latency across
        every serving batcher (``serving.batcher.latency_s``).
      max_rejected_fraction: budget for shed traffic — batcher fast-fail
        rejections plus admission-control sheds, over all finished
        requests.
      max_timeout_fraction: budget for requests whose deadline expired
        before their batch executed.
      max_step_regression: trailing-window training step time over the
        run's own opening-baseline window (1.5 = "no more than 50%
        slower than the run started out").
      max_residual_drift: worst acceptable served-residual drift across
        the fleet's drift-monitored tenants — the windowed shadow-probe
        residual over the tenant's own attach-time baseline
        (``fleet.drift.level`` gauges, written by
        :class:`~tensordiffeq_tpu.fleet.DriftMonitor`; 3.0 = "a tenant
        may degrade to 3x its export-time residual before the retrain
        loop owes a response").  Like every objective, no monitored
        traffic means no verdict (``ok=None``), not a breach.
      min_replica_availability: the one HIGHER-IS-BETTER objective —
        worst acceptable fraction of a replica group's front-tier
        endpoints that are reachable (``fleet.replica.availability``
        gauges, written by
        :class:`~tensordiffeq_tpu.fleet.FrontRouter`; 0.99 = "at most
        1% of replica capacity may be breaker-open").  Its burn rate is
        the UNAVAILABLE fraction over the unavailability budget, so >1
        still means "error budget burning" like every other objective.
      window: events per window for the step-regression comparison.
    """

    def __init__(self, serving_p99_s: float = 0.25,
                 max_rejected_fraction: float = 0.05,
                 max_timeout_fraction: float = 0.01,
                 max_step_regression: float = 1.5,
                 max_residual_drift: float = 3.0,
                 min_replica_availability: float = 0.99,
                 window: int = 20):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if max_residual_drift <= 0:
            raise ValueError("max_residual_drift must be > 0, got "
                             f"{max_residual_drift}")
        if not 0.0 < float(min_replica_availability) <= 1.0:
            raise ValueError("min_replica_availability must be in (0, 1], "
                             f"got {min_replica_availability}")
        self.serving_p99_s = float(serving_p99_s)
        self.max_rejected_fraction = float(max_rejected_fraction)
        self.max_timeout_fraction = float(max_timeout_fraction)
        self.max_step_regression = float(max_step_regression)
        self.max_residual_drift = float(max_residual_drift)
        self.min_replica_availability = float(min_replica_availability)
        self.window = int(window)

    @classmethod
    def default(cls) -> "SLOSet":
        return cls()

    # ------------------------------------------------------------------ #
    def evaluate(self, metrics, events: Optional[list] = None) -> dict:
        """Verdict over a registry (or its ``as_dict()`` snapshot) and,
        when ``events`` are given, the run's ``step_time`` trail for the
        regression objective."""
        if hasattr(metrics, "as_dict"):  # a registry (or registry-like)
            metrics = metrics.as_dict()
        metrics = metrics or {}

        served = _sum_counters(metrics, "serving.batcher.requests")
        failed = _sum_counters(metrics, "serving.batcher.failed")
        timed_out = _sum_counters(metrics, "serving.batcher.timed_out")
        rejected = (_sum_counters(metrics, "serving.batcher.rejected")
                    + _sum_counters(metrics, "fleet.admission.rejected"))
        finished = served + failed + timed_out + rejected

        objectives = {
            "serving_p99_s": _objective(
                _max_hist_p99(metrics, "serving.batcher.latency_s"),
                self.serving_p99_s),
            "rejected_fraction": _objective(
                round(rejected / finished, 6) if finished else None,
                self.max_rejected_fraction),
            "timed_out_fraction": _objective(
                round(timed_out / finished, 6) if finished else None,
                self.max_timeout_fraction),
            "step_time_regression": _objective(
                self._step_regression(events or []),
                self.max_step_regression),
            # served-residual drift (PR 18): the closed loop's trip wire.
            # The DriftMonitor writes one fleet.drift.level gauge per
            # monitored tenant (windowed probe residual / attach-time
            # baseline); the objective judges the worst of them, and its
            # burn_rate is what arms the RetrainController
            "residual_drift": _objective(
                _max_gauge(metrics, "fleet.drift.level"),
                self.max_residual_drift),
        }
        # replica availability (PR 20) is higher-is-better, so _objective's
        # value<=threshold comparison is inverted here: ok when the WORST
        # group's availability still clears the floor, burn rate = observed
        # unavailable fraction over the unavailability budget
        avail = _min_gauge(metrics, "fleet.replica.availability")
        floor = self.min_replica_availability
        objectives["replica_availability"] = {
            "value": avail, "threshold": floor,
            "ok": None if avail is None else bool(avail >= floor),
            "burn_rate": None if avail is None else round(
                (1.0 - avail) / max(1.0 - floor, 1e-9), 4),
        }
        breaches = sorted(k for k, o in objectives.items()
                          if o["ok"] is False)
        return {"ok": not breaches, "objectives": objectives,
                "breaches": breaches}

    def _step_regression(self, events: list) -> Optional[float]:
        """Trailing-window mean per-step time over the opening-baseline
        window, from ``step_time`` events (any phase, per-step
        normalised).  None until both windows have data — and the two
        windows must not overlap, or a short run would compare a sample
        against itself."""
        per_step = []
        for e in events:
            if e.get("kind") != "step_time":
                continue
            n = e.get("n_steps") or 0
            total = sum(float(e.get(k) or 0.0)
                        for k in ("dispatch_s", "device_s", "data_s"))
            if n and total > 0:
                per_step.append(total / n)
        if len(per_step) < 2 * self.window:
            return None
        base = per_step[:self.window]
        cur = per_step[-self.window:]
        baseline = sum(base) / len(base)
        current = sum(cur) / len(cur)
        if baseline <= 0:
            return None
        return round(current / baseline, 4)

    def evaluate_run(self, run_dir: str) -> dict:
        """Verdict for a run directory: the manifest's closing metrics
        snapshot (empty for a killed run — objectives then report no
        data rather than a fake pass/fail) + the events trail."""
        try:
            metrics = read_manifest(run_dir).get("metrics") or {}
        except OSError:
            metrics = {}
        return self.evaluate(metrics, read_events(run_dir))


# -------------------------------------------------------------------------- #
# Prometheus text exposition
# -------------------------------------------------------------------------- #
def _prom_name(name: str) -> str:
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return out if not out[:1].isdigit() else "_" + out


def _prom_label_value(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n").replace(
        '"', r'\"')


def _prom_labels(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{_prom_name(k)}="{_prom_label_value(v)}"'
                     for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def to_prometheus(metrics) -> str:
    """Render a :class:`~tensordiffeq_tpu.telemetry.MetricsRegistry` (or
    its ``as_dict()``) in Prometheus text exposition format 0.0.4.

    Counters keep their value under ``<name>_total``; gauges render
    plainly; histograms render as Prometheus *summaries* (``quantile``
    labels from the reservoir percentiles, plus ``_sum`` / ``_count``)
    with min/max as companion gauges.  Dots become underscores; unset
    gauges and empty histograms are skipped (no fake zeros).  Pure
    formatter — serve the string from any handler you already run."""
    if hasattr(metrics, "as_dict"):  # a registry (or registry-like)
        metrics = metrics.as_dict()
    metrics = metrics or {}
    lines = []
    typed = set()

    def head(pname: str, ptype: str):
        if pname not in typed:
            typed.add(pname)
            lines.append(f"# TYPE {pname} {ptype}")

    for key, v in sorted((metrics.get("counters") or {}).items()):
        if not isinstance(v, (int, float)):
            continue
        base, labels = _parse_key(key)
        pname = _prom_name(base) + "_total"
        head(pname, "counter")
        lines.append(f"{pname}{_prom_labels(labels)} {v}")

    for key, v in sorted((metrics.get("gauges") or {}).items()):
        if not isinstance(v, (int, float)):
            continue
        base, labels = _parse_key(key)
        pname = _prom_name(base)
        head(pname, "gauge")
        lines.append(f"{pname}{_prom_labels(labels)} {v}")

    # histograms: group by family FIRST — the exposition format requires
    # every sample of a metric family to be one contiguous block, so the
    # summary lines of all labeled instances are emitted together and the
    # companion _min/_max gauge families follow as their own blocks
    # (interleaving them per instance would split the summary family and
    # fail strict parsers on multi-tenant registries)
    families: dict = {}
    for key, summ in sorted((metrics.get("histograms") or {}).items()):
        if not isinstance(summ, dict) or not summ.get("count"):
            continue
        base, labels = _parse_key(key)
        families.setdefault(base, []).append((labels, summ))
    for base, instances in sorted(families.items()):
        pname = _prom_name(base)
        head(pname, "summary")
        for labels, summ in instances:
            for q in ("p50", "p90", "p99"):
                qv = summ.get(q)
                if isinstance(qv, (int, float)):
                    lines.append(
                        f"{pname}"
                        f"{_prom_labels(labels, {'quantile': '0.' + q[1:]})}"
                        f" {qv}")
            lines.append(f"{pname}_sum{_prom_labels(labels)} {summ['sum']}")
            lines.append(
                f"{pname}_count{_prom_labels(labels)} {summ['count']}")
        for bound in ("min", "max"):
            rows = [(labels, summ[bound]) for labels, summ in instances
                    if isinstance(summ.get(bound), (int, float))]
            if not rows:
                continue
            bname = f"{pname}_{bound}"
            head(bname, "gauge")
            for labels, bv in rows:
                lines.append(f"{bname}{_prom_labels(labels)} {bv}")
    return "\n".join(lines) + ("\n" if lines else "")
