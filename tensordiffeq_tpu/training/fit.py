"""Training engine: jit-compiled Adam (+SA minimax) epoch loops.

TPU-native replacement for the reference's eager epoch loop
(``fit.py:17-102``): instead of one Python-dispatched ``tf.function`` call
per epoch, whole *chunks* of epochs run inside a single ``lax.scan`` under
one ``jax.jit`` — the device never waits on the host between steps, and on a
sharded collocation batch XLA turns the loss means into ICI all-reduces
automatically (the design replacing ``MirroredStrategy``/``strategy.reduce``,
reference ``models.py:235``, ``fit.py:183-187``).

Self-adaptive λ ascent is a single ``optax.multi_transform``: network params
get Adam; λ get ``scale(-1) → Adam`` — gradient *ascent*, the SA-PINN minimax
of reference ``fit.py:135-141`` without its dual-optimizer bookkeeping.

Minibatching scans over pre-reshaped ``[n_batches, bsz, d]`` shards and runs
**every** batch each epoch (the reference's loop returns after batch 0 —
SURVEY §2.4.1), and composes with SA weights by gathering λ rows alongside
their points (lifting the reference restriction at ``models.py:228-229``).
Under ``dist=True`` the batches are built **per device shard** — each batch
takes ``bsz / n_dev`` contiguous rows from every device's slice of the
collocation set, so batching never reshapes across the sharded point axis
and every batch keeps the global-batch semantics of the reference's
distributed dataset (``models.py:252-263``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..resilience.chaos import active_chaos
from ..resilience.cluster import beat
from ..resilience.preemption import (Preempted, note_final_flush,
                                     preemption_requested)
from ..telemetry import log_event
from ..utils import tree_copy
from .progress import progress_bar


def make_optimizer(lr: "float | Callable" = 0.005,
                   lr_weights: "float | Callable" = 0.005,
                   b1: float = 0.99, freeze_lambdas: bool = False,
                   grad_clip: Optional[float] = None
                   ) -> optax.GradientTransformation:
    """Adam for the network + Adam-ascent for λ (reference defaults
    ``lr=0.005, beta_1=0.99``, ``models.py:49-50``), as one transform.

    ``freeze_lambdas=True`` pins λ inside the scan (used by NTK weighting,
    where λ are recomputed analytically between chunks, not trained).

    ``grad_clip``: global-norm gradient clipping bound applied ahead of
    both transforms (the divergence-recovery remedy rung — see
    :class:`~tensordiffeq_tpu.resilience.ResilientFit`).  Note it changes
    the optimizer-state pytree, so a checkpoint saved without clipping
    resumes with fresh Adam moments when clipping is turned on (intended:
    the old moments were aimed at the divergence)."""

    def label_fn(trainables):
        return {
            "params": jax.tree_util.tree_map(lambda _: "net", trainables["params"]),
            "lambdas": jax.tree_util.tree_map(lambda _: "lam", trainables["lambdas"]),
        }

    lam_tx = (optax.set_to_zero() if freeze_lambdas
              else optax.chain(optax.scale(-1.0), optax.adam(lr_weights, b1=b1)))
    net_tx = optax.adam(lr, b1=b1)
    if grad_clip is not None:
        clip = optax.clip_by_global_norm(float(grad_clip))
        net_tx = optax.chain(clip, net_tx)
        if not freeze_lambdas:
            lam_tx = optax.chain(optax.clip_by_global_norm(float(grad_clip)),
                                 lam_tx)
    return optax.multi_transform({"net": net_tx, "lam": lam_tx}, label_fn)


def opt_state_matches(opt, trainables, opt_state) -> bool:
    """True iff ``opt_state`` has the structure and leaf shapes that
    ``opt.init(trainables)`` would produce — a resumed state must match or
    the mismatch surfaces as an opaque error deep inside jit."""
    want = jax.eval_shape(opt.init, trainables)
    if (jax.tree_util.tree_structure(want)
            != jax.tree_util.tree_structure(opt_state)):
        return False
    return all(tuple(np.shape(a)) == tuple(w.shape)
               for a, w in zip(jax.tree_util.tree_leaves(opt_state),
                               jax.tree_util.tree_leaves(want)))


def make_batches(X_f, batch_sz: Optional[int], mesh=None, verbose: bool = True,
                 permute: bool = False):
    """Slice the collocation set into scan-able batches.

    Returns ``(X_batched [n_b, bsz, d], idx_batched [n_b, bsz], n_batches)``
    where ``idx_batched`` maps each batch row back to its global point row
    (for gathering per-point SA λ).

    **Every row trains**: batching is ceil-batching with wraparound — when
    ``batch_sz`` does not divide the point count, the tail batch wraps to
    the front of the set instead of dropping the remainder (the quiet data
    loss the reference's loop had in a worse form, SURVEY §2.4.1, reference
    ``fit.py:128-145``).  Wrapped rows simply get one extra gradient
    contribution per epoch; with per-point SA λ the gather rides the same
    index map, so λ rows wrap identically.  Under ``mesh`` the guarantee
    is per-shard: the point count must already be a device multiple (the
    data-parallel placement, :func:`..parallel.shard_data_inputs`, trims
    to one up front with its own message) — a non-multiple passed here
    directly leaves the last ``N_f % n_dev`` rows outside every shard
    block, and is warned about.

    Single device: contiguous reshape (+wraparound tail).  With ``mesh``
    (data-parallel training): **per-shard batching** — device k owns the
    contiguous row block ``[k·N/n_dev, (k+1)·N/n_dev)`` of ``X_f`` and λ,
    and batch b takes rows ``b·bszₗ:(b+1)·bszₗ`` of EVERY device's block
    (``bszₗ = bsz/n_dev``, wrapping within the block), so each ``[bsz, d]``
    batch is itself sharded over ``"data"``, the λ-row gather stays
    device-local, and no reshape ever crosses the sharded point axis.
    Matches the reference's global-batch semantics (``models.py:252-263``:
    global batch = per-replica × replicas).

    ``permute=True``: a fixed seeded shuffle of the row order before
    batching — WITHIN each device's block under ``mesh``, so the λ gather
    stays device-local.  Required for ORDERED point sets (meshgrid
    observation grids): a contiguous batch there is a thin coordinate slab,
    measured to destabilise inverse-problem coefficients (spatially biased
    gradients).  LHS collocation draws are already unordered, so the
    forward solver keeps the default."""
    N_f = int(X_f.shape[0])
    if batch_sz is None or batch_sz >= N_f:
        n_batches, bsz = 1, N_f
    else:
        bsz = int(batch_sz)
        if mesh is not None:
            n_dev = int(np.prod(mesh.devices.shape))
            if bsz % n_dev:
                orig = bsz
                bsz = max(bsz - bsz % n_dev, n_dev)
                log_event("fit", f"batch_sz {orig} -> {bsz} so each of "
                          f"the {n_dev} devices gets equal batch rows",
                          verbose=verbose)
        n_batches = -(-N_f // bsz)  # ceil: keep every row

    if mesh is not None and n_batches > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel import DATA_AXIS
        n_dev = int(np.prod(mesh.devices.shape))
        shard_rows = N_f // n_dev
        if shard_rows * n_dev != N_f:
            # normal dist flows never hit this (shard_data_inputs trims to
            # a device multiple first); a direct caller should know
            log_event("fit", f"{N_f % n_dev} rows beyond the {n_dev}-device "
                      "multiple fall outside every shard block and never "
                      "train", verbose=verbose, level="warning")
        bsz_local = bsz // n_dev
        n_batches = -(-shard_rows // bsz_local)  # ceil: keep every row
        if permute:
            rs = np.random.RandomState(0)
            base = np.stack([rs.permutation(shard_rows)
                             for _ in range(n_dev)])
        else:
            base = np.tile(np.arange(shard_rows), (n_dev, 1))
        # wraparound within each device's block: the tail batch reuses
        # rows from the front of the SAME shard, keeping the gather local
        take = np.arange(n_batches * bsz_local) % shard_rows
        if take.size != shard_rows:
            log_event("fit", f"tail batch wraps {take.size - shard_rows} "
                      f"rows per shard so {bsz}-point batches cover every "
                      "point", verbose=verbose)
        idx = base[:, take] + (np.arange(n_dev) * shard_rows)[:, None]
        idx = idx.reshape(n_dev, n_batches, bsz_local)
        idx = np.swapaxes(idx, 0, 1).reshape(n_batches, bsz)  # [n_b, bsz]
        # gather ON DEVICE (a host np.asarray round-trip would both move the
        # whole set through the host and fail outright on multi-host meshes
        # where X_f spans non-addressable devices), then place the batch
        # layout; each device's target rows come from its own shard, so the
        # reshard is local
        X_batched = jax.device_put(
            jnp.take(X_f, jnp.asarray(idx), axis=0),
            NamedSharding(mesh, P(None, DATA_AXIS, None)))
        idx_batched = jax.device_put(
            jnp.asarray(idx), NamedSharding(mesh, P(None, DATA_AXIS)))
    elif n_batches > 1:
        take = np.arange(n_batches * bsz) % N_f
        if take.size != N_f:
            log_event("fit", f"tail batch wraps {take.size - N_f} rows so "
                      f"{bsz}-point batches cover every point",
                      verbose=verbose)
        if permute:
            idx = np.random.RandomState(0).permutation(N_f)[take]
        else:
            idx = take
        X_batched = jnp.take(X_f, jnp.asarray(idx), axis=0).reshape(
            n_batches, bsz, -1)
        idx_batched = jnp.asarray(idx).reshape(n_batches, bsz)
    else:
        X_batched = X_f[: n_batches * bsz].reshape(n_batches, bsz, -1)
        idx_batched = jnp.arange(n_batches * bsz).reshape(n_batches, bsz)
    return X_batched, idx_batched, n_batches


@dataclass
class FitResult:
    """Host-side training record (parity with the reference's ``self.losses``
    history and best-model tracking, ``models.py:17-25,117``)."""
    losses: list = field(default_factory=list)
    min_loss: dict = field(default_factory=lambda: {"adam": np.inf,
                                                    "l-bfgs": np.inf,
                                                    "overall": np.inf})
    best_epoch: dict = field(default_factory=lambda: {"adam": -1,
                                                      "l-bfgs": -1,
                                                      "overall": -1})
    best_params: dict = field(default_factory=lambda: {"adam": None,
                                                       "l-bfgs": None,
                                                       "overall": None})
    wall_time: dict = field(default_factory=dict)


def _chunk_runner(loss_fn: Callable, opt: optax.GradientTransformation,
                  n_batches: int, n_points: int,
                  with_grad_norm: bool = False):
    """Build the jitted multi-step runner.

    Returns ``run(trainables, opt_state, best, X_batched, idx_batched,
    step0, n_steps) -> (trainables, opt_state, best, components)`` executing
    ``n_steps`` optimizer steps in one on-device ``lax.scan``.

    ``best`` carries ``(params_snapshot, best_loss, best_step)`` and is
    updated with a pytree select each step — a true copy, fixing the
    reference's aliasing best-model bug (SURVEY §2.4.6).

    ``with_grad_norm=True`` (set when a telemetry subscriber is attached)
    adds the optimizer-step gradient global-norm to the per-step components
    as ``"Grad_norm"`` — one extra scalar reduction inside the compiled
    step, the only piece of instrumentation that lives on-device.
    """

    def _is_per_point(lam):
        return lam is not None and lam.ndim >= 1 and lam.shape[0] == n_points

    def loss_over_trainables(trainables, X_b, idx_b):
        lambdas = trainables["lambdas"]
        if n_batches == 1:
            lam_res = lambdas["residual"]
        else:
            # gather only per-point λ alongside their batch rows; scalar
            # (type-2) λ apply to the whole term and pass through untouched
            lam_res = [lam[idx_b] if _is_per_point(lam) else lam
                       for lam in lambdas["residual"]]
        lam_data = lambdas.get("data", (None,))[0]
        return loss_fn(trainables["params"], lambdas["BCs"], lam_res, X_b,
                       lam_data=lam_data)

    grad_fn = jax.value_and_grad(loss_over_trainables, has_aux=True)

    # donate the carried state: each chunk reuses the previous chunk's
    # buffers instead of allocating fresh ones (callers pass copies in)
    @partial(jax.jit, static_argnames=("n_steps",), donate_argnums=(0, 1, 2))
    def run(trainables, opt_state, best, X_batched, idx_batched, step0,
            n_steps: int):
        def step(carry, i):
            trainables, opt_state, best = carry
            b = i % n_batches
            X_b = X_batched[b] if n_batches > 1 else X_batched[0]
            idx_b = idx_batched[b] if n_batches > 1 else idx_batched[0]
            (total, comps), grads = grad_fn(trainables, X_b, idx_b)
            if with_grad_norm:
                comps = {**comps, "Grad_norm": optax.global_norm(grads)}
            updates, opt_state = opt.update(grads, opt_state, trainables)
            trainables = optax.apply_updates(trainables, updates)

            best_params, best_loss, best_step = best
            improved = total < best_loss
            best = (
                jax.tree_util.tree_map(
                    lambda new, old: jnp.where(improved, new, old),
                    trainables["params"], best_params),
                jnp.where(improved, total, best_loss),
                jnp.where(improved, step0 + i, best_step),
            )
            return (trainables, opt_state, best), comps

        (trainables, opt_state, best), comps = jax.lax.scan(
            step, (trainables, opt_state, best), jnp.arange(n_steps))
        return trainables, opt_state, best, comps

    return run


def _carry_lambda_rows(trainables, opt_state, is_rows, carry):
    """The ONE λ-carry walker every resample flavor shares (the solver's
    per-point path and the factory's per-member family path): residual λ
    terms matching ``is_rows`` are remapped through ``carry(leaf,
    fresh_zero)`` (kept rows ride, fresh rows initialize per the
    adaptive schedule / at zero for moments), and the λ-ascent Adam
    moments follow the same map — walked by PATH on the optimizer
    state's ``lam`` branch, so a BC λ (or a network layer) whose size
    coincides with the row count is never mis-carried.  Returns
    ``(trainables, opt_state, drift)`` with ``drift`` None when no
    matching λ exist (nothing to carry).  One implementation so a
    future fix to the path/shape guards applies to every flavor."""
    drift = None
    new_terms = []
    for lam in trainables["lambdas"]["residual"]:
        if is_rows(lam):
            lam, d = carry(lam, False)
            drift = d if drift is None else jnp.maximum(drift, d)
        new_terms.append(lam)
    if drift is None:
        return trainables, opt_state, None
    trainables = {"params": trainables["params"],
                  "lambdas": {**trainables["lambdas"],
                              "residual": new_terms}}

    def _on_residual_path(path):
        return any(getattr(k, "key", None) == "residual" for k in path)

    def remap(path, a):
        if _on_residual_path(path) and is_rows(a):
            return carry(a, True)[0]
        return a

    inner = getattr(opt_state, "inner_states", None)
    if isinstance(inner, dict) and "lam" in inner:
        new_inner = dict(inner)
        new_inner["lam"] = jax.tree_util.tree_map_with_path(
            remap, inner["lam"])
        opt_state = opt_state._replace(inner_states=new_inner)
    return trainables, opt_state, drift


def _carry_point_state(trainables, opt_state, swap, n_points: int):
    """Carry per-point SA state through a :class:`~tensordiffeq_tpu.ops.
    resampling.DeviceResampler` redraw: per-point residual λ rows gather
    through ``swap.idx`` (kept rows ride, fresh rows initialize from the
    adaptive schedule — see :func:`..ops.resampling.carry_rows`), and the
    λ-ascent Adam moments follow the same map with fresh rows restarting
    at zero (a fresh point has no ascent history).  The walking/guard
    logic lives in :func:`_carry_lambda_rows`."""
    from ..ops.resampling import carry_rows

    def _is_rows(a):
        return (a is not None and getattr(a, "ndim", 0) >= 1
                and int(a.shape[0]) == n_points)

    def carry(a, fresh_zero):
        return carry_rows(a, swap.idx, swap.kept, fresh_zero=fresh_zero)

    return _carry_lambda_rows(trainables, opt_state, _is_rows, carry)


def _adopt_points(X_new, X_f, batch_sz, mesh, best):
    """Adopt a redrawn collocation set mid-fit — the bookkeeping BOTH
    resample paths (synchronous host, pipelined device swap) share:
    shape guard (the redraw must keep N_f so the compiled step is
    reused), batch-buffer rebuild, and the best-model threshold reset —
    losses before/after a redraw are measured on different point sets
    (importance sampling deliberately picks harder points), so best-model
    tracking must keep competing on the new set instead of freezing at a
    pre-redraw snapshot.  Returns ``(X_f, X_batched, idx_batched,
    best)``."""
    if X_new.shape != X_f.shape:
        raise ValueError(
            f"resample redraw changed the collocation shape "
            f"{X_f.shape} -> {X_new.shape}; the redraw must keep N_f so "
            "the compiled step is reused")
    X_batched, idx_batched, _ = make_batches(X_new, batch_sz, mesh=mesh,
                                             verbose=False)
    return X_new, X_batched, idx_batched, (best[0], jnp.asarray(jnp.inf),
                                           best[2])


def fit_adam(loss_fn: Callable,
             params,
             lambdas,
             X_f: jnp.ndarray,
             tf_iter: int,
             batch_sz: Optional[int] = None,
             lr: "float | Callable" = 0.005,
             lr_weights: "float | Callable" = 0.005,
             chunk: int = 100,
             verbose: bool = True,
             result: Optional[FitResult] = None,
             opt_state: Any = None,
             freeze_lambdas: bool = False,
             lambda_update_fn: Optional[Callable] = None,
             mesh=None,
             callback: Optional[Callable] = None,
             callback_every: int = 0,
             resample_fn: Optional[Callable] = None,
             resample_every: int = 0,
             state_hook: Optional[Callable] = None,
             state_hook_every: int = 0,
             stop_fn: Optional[Callable] = None,
             telemetry: Optional[Any] = None,
             grad_clip: Optional[float] = None,
             epoch0: int = 0,
             ) -> tuple[Any, Any, FitResult]:
    """Run the Adam(+SA) phase.  Returns ``(trainables, result)`` with
    ``trainables = {"params":…, "lambdas":…}`` at the final step and the
    training record (losses per epoch, best snapshot).

    ``mesh``: the data-parallel device mesh when ``X_f`` (and per-point λ)
    are sharded along their leading axis — batches are then built per device
    shard (see module docstring) instead of by a contiguous reshape, which
    would split the sharded axis.

    ``callback(epoch, params)`` fires at chunk boundaries whenever the epoch
    count crosses a multiple of ``callback_every`` — periodic evaluation
    (e.g. rel-L2 timelines) WITHOUT splitting training into separate fit
    calls, so the jitted runner and optimizer state stay warm.

    ``resample_fn(params, epoch) -> X_new`` + ``resample_every``: adaptive
    collocation redraw (:mod:`..ops.resampling`) at the same chunk-boundary
    cadence.  ``X_new`` must keep the original shape/sharding, so the
    compiled runner and optimizer state carry straight on — only the batch
    buffers are rebuilt.  A *pipelined* hook (``resample_fn.pipelined``
    True, exposing ``dispatch(params, X_f, epoch) -> ResampleSwap``)
    is instead double-buffered: the redraw is DISPATCHED at the due
    boundary (jax async dispatch — the host returns immediately) and its
    buffers swap in at the NEXT boundary, so pool scoring executes behind
    the intervening training chunk instead of serializing with it; the
    swap also carries per-point residual λ (and their λ-ascent moments)
    through the redraw (:func:`_carry_point_state`).  A redraw still
    pending when the phase ends is discarded.

    ``state_hook(trainables, opt_state, epoch, best=...)`` +
    ``state_hook_every``: chunk-boundary access to the LIVE optimizer
    state (the solver object only syncs after the phase returns) — the
    mid-run checkpoint path, so a killed long run resumes instead of
    restarting.  ``best`` is the phase's live running best
    ``(params_snapshot, best_loss, best_epoch)`` so checkpoints can carry
    the best iterate, not just the final one.  Fires before ``callback``
    at the same boundary, so a checkpoint written here is never newer
    than the evaluation recorded after it.

    ``stop_fn(result) -> bool``: checked at chunk boundaries; returning
    True ends the phase early with the state as of that boundary (the
    staged causal-ε ladder uses this to hand the remaining budget to the
    next ε stage the moment the causal gate opens).

    ``telemetry``: a :class:`~tensordiffeq_tpu.telemetry.TrainingTelemetry`
    subscriber.  When attached, the compiled step also returns the gradient
    global-norm (``"Grad_norm"`` in the loss history — a different jit key,
    so toggling it recompiles once), and each chunk boundary reports
    per-epoch loss rows, the SA-λ distribution summaries, the
    dispatch/device/data step-time split (``block_until_ready``-fenced),
    and runs the NaN/Inf sentinel — which may raise
    :class:`~tensordiffeq_tpu.telemetry.TrainingDiverged`.

    ``grad_clip``: global-norm gradient clipping inside the optimizer
    (see :func:`make_optimizer`) — the divergence-recovery remedy rung.

    ``epoch0``: absolute epoch of this call's first step, used ONLY by the
    resilience layer (chaos epoch triggers and preemption events are keyed
    to absolute run epochs, so they stay meaningful across rollback/resume
    legs); the loop's own bookkeeping stays call-relative.  Chunk
    boundaries also run the chaos hooks (when a
    :class:`~tensordiffeq_tpu.resilience.Chaos` plan is active) and the
    preemption check: a pending request flushes a final checkpoint through
    ``state_hook`` and raises
    :class:`~tensordiffeq_tpu.resilience.Preempted`."""
    result = result or FitResult()
    N_f = X_f.shape[0]
    X_batched, idx_batched, n_batches = make_batches(
        X_f, batch_sz, mesh=mesh, verbose=verbose)

    opt = make_optimizer(lr, lr_weights, freeze_lambdas=freeze_lambdas,
                         grad_clip=grad_clip)
    # copy: the chunk runner donates its carried state, and the caller's
    # arrays (solver.params / restored opt_state) must stay valid
    trainables = tree_copy({"params": params, "lambdas": lambdas})
    if lambda_update_fn is not None:  # e.g. NTK: balance before step 0
        trainables["lambdas"] = lambda_update_fn(trainables["params"])
    if opt_state is None:
        opt_state = opt.init(trainables)
    elif not opt_state_matches(opt, trainables, opt_state):
        raise ValueError(
            "opt_state does not match the current trainables (structure or "
            "shapes differ); was the checkpoint saved for a different "
            "configuration?")
    else:
        opt_state = tree_copy(opt_state)
    # classify per-point λ by the full point count: λ keeps all N_f rows and
    # batch rows gather from them (the wraparound tail re-gathers front rows)
    run = _chunk_runner(
        loss_fn, opt, n_batches, N_f,
        with_grad_norm=(telemetry is not None
                        and getattr(telemetry, "grad_norm", True)))

    best = (tree_copy(params), jnp.inf, jnp.asarray(-1))
    total_steps = tf_iter * n_batches
    if telemetry is not None and total_steps > 0 \
            and hasattr(telemetry, "on_step_program"):
        # price the step program for the live cost.* gauges: lowering the
        # SAME jitted runner at the first chunk's signature reads the HLO
        # cost analysis WITHOUT a second XLA compile (Lowered.cost_analysis)
        # and without touching the program the loop executes
        n0 = int(min(chunk * n_batches, total_steps))
        telemetry.on_step_program(
            "adam",
            lambda: run.lower(trainables, opt_state, best, X_batched,
                              idx_batched, jnp.asarray(0), n0),
            n_steps=n0)
    t0 = time.time()
    steps_done = 0
    data_s = 0.0  # batch-rebuild (resample) time attributed to step-time
    # device-resident resample hooks (ops.resampling.DeviceResampler via
    # the solver's wrapper) are double-buffered: `pending` holds a redraw
    # dispatched at the previous chunk boundary, swapped in at the next
    res_pipelined = bool(getattr(resample_fn, "pipelined", False))
    pending = None
    pbar = progress_bar(tf_iter, desc="Adam") if verbose else None
    while steps_done < total_steps:
        n = int(min(chunk * n_batches, total_steps - steps_done))
        t_chunk0 = time.perf_counter()
        trainables, opt_state, best, comps = run(
            trainables, opt_state, best, X_batched, idx_batched,
            jnp.asarray(steps_done), n)
        if telemetry is not None:
            # fence host dispatch vs device execution: run() returns as
            # soon as the scan is dispatched; the block measures what the
            # device is still busy with
            t_disp = time.perf_counter() - t_chunk0
            # tdq: allow[host-sync-in-hot-path] THE fenced telemetry point: one deliberate fence per chunk prices dispatch vs device wait
            jax.block_until_ready(comps)
            t_dev = time.perf_counter() - t_chunk0 - t_disp
        # tdq: allow[host-sync-in-hot-path] per-chunk loss-history transfer: comps are already computed; one pull per chunk, not per step
        comps = jax.tree_util.tree_map(np.asarray, comps)
        # record one entry per epoch (last batch of each epoch)
        for e in range(n // n_batches):
            i = (e + 1) * n_batches - 1
            result.losses.append({k: float(v[i]) for k, v in comps.items()})
        prev_epochs = steps_done // n_batches
        steps_done += n
        cur_epochs = steps_done // n_batches
        # cluster heartbeat: the host comps transfer above already fenced
        # the device, so this beat certifies real forward progress (no-op
        # without a supervisor — one cached dict probe)
        beat("adam", epoch0 + cur_epochs)
        if telemetry is not None:
            n_ep = cur_epochs - prev_epochs
            rows = result.losses[-n_ep:] if n_ep else []
            telemetry.on_step_time("adam", n, t_disp, t_dev, data_s)
            data_s = 0.0
            telemetry.on_epoch_rows("adam", prev_epochs, rows)
            telemetry.on_lambda_stats(cur_epochs, trainables["lambdas"])
            try:
                telemetry.check_rows("adam", prev_epochs, rows)
            except Exception:
                if pbar is not None:
                    pbar.close()
                raise
        if pending is not None and steps_done >= total_steps:
            # phase over: DISCARD the pending redraw (the docstring
            # contract) — adopting it here would hand later phases
            # (L-BFGS) a point set, and carry-reset fresh-row λ, that
            # never trained a single Adam step.  The sync path never
            # redraws at the final boundary for the same reason.
            pending = None
        if pending is not None:
            # double-buffered swap: the redraw DISPATCHED at the previous
            # boundary executed behind the chunk that just ran — adopt its
            # point set now.  Host-visible cost is the swap bookkeeping
            # (plus any residual device wait if the redraw outran the
            # chunk), never the pool scoring itself.
            swap, disp_epoch, disp_s = pending
            pending = None
            t_sw = time.perf_counter()
            X_f, X_batched, idx_batched, best = _adopt_points(
                swap.X_new, X_f, batch_sz, mesh, best)
            trainables, opt_state, drift = _carry_point_state(
                trainables, opt_state, swap, int(X_f.shape[0]))
            on_swap = getattr(resample_fn, "on_swap", None)
            if on_swap is not None:
                on_swap(X_f)
            want_stats = (telemetry is not None
                          and hasattr(telemetry, "on_resample"))
            if want_stats:
                # this host transfer blocks until the redraw program has
                # actually finished, so any residual device wait (the
                # redraw outran the chunk) lands in the measured stall
                # rather than leaking into the next chunk's timings
                stats = {k: float(v) for k, v in swap.stats.items()}
            stall = time.perf_counter() - t_sw
            data_s += stall
            if want_stats:
                if drift is not None:
                    stats["lambda_drift"] = float(drift)
                flops_info = getattr(resample_fn, "flops_info", None)
                telemetry.on_resample(
                    "adam", cur_epochs, disp_s + stall, stats=stats,
                    pipelined=True, dispatched_epoch=disp_epoch,
                    flops=(flops_info() if flops_info is not None
                           else (None, None)))
        if (resample_fn is not None and resample_every > 0
                and steps_done < total_steps
                and prev_epochs // resample_every != cur_epochs // resample_every):
            if res_pipelined:
                # dispatch only: jax async dispatch returns in ~ms while
                # the device scores the pool behind the NEXT chunk; the
                # buffers swap at the next boundary (one-chunk staleness,
                # the PACMANN-style pipelining trade).  The score pass's
                # FLOPs are credited NOW — they execute inside the next
                # chunk's wall, and the cost model must not read that
                # device time as idle training time.  Pricing (a one-off
                # ms-scale lowering) runs before the stall timer so the
                # first redraw's measured stall stays honest.
                flops_info = getattr(resample_fn, "flops_info", None)
                if telemetry is not None and flops_info is not None \
                        and hasattr(telemetry, "note_resample_flops"):
                    telemetry.note_resample_flops(flops_info()[0])
                t_data0 = time.perf_counter()
                swap_next = resample_fn.dispatch(trainables["params"], X_f,
                                                 cur_epochs)
                disp_s = time.perf_counter() - t_data0
                pending = (swap_next, cur_epochs, disp_s)
                data_s += disp_s
            else:
                t_data0 = time.perf_counter()
                X_new = resample_fn(trainables["params"], cur_epochs)
                X_f, X_batched, idx_batched, best = _adopt_points(
                    X_new, X_f, batch_sz, mesh, best)
                stall = time.perf_counter() - t_data0
                data_s += stall
                if telemetry is not None and hasattr(telemetry,
                                                     "on_resample"):
                    flops_info = getattr(resample_fn, "flops_info", None)
                    telemetry.on_resample(
                        "adam", cur_epochs, stall, stats=None,
                        pipelined=False,
                        flops=(flops_info() if flops_info is not None
                               else (None, None)))
        if lambda_update_fn is not None and steps_done < total_steps:
            # after any redraw, so NTK balances the points actually trained
            trainables["lambdas"] = lambda_update_fn(trainables["params"])
        if (state_hook is not None and state_hook_every > 0
                and prev_epochs // state_hook_every
                != cur_epochs // state_hook_every):
            state_hook(trainables, opt_state, cur_epochs,
                       best=(best[0], best[1],
                             int(best[2]) // max(n_batches, 1)))
        if (callback is not None and callback_every > 0
                and prev_epochs // callback_every != cur_epochs // callback_every):
            callback(cur_epochs, trainables["params"])
        if pbar is not None:
            pbar.update(n // n_batches)
            pbar.set_postfix(loss=result.losses[-1]["Total Loss"])
        if stop_fn is not None and stop_fn(result):
            break
        if steps_done < total_steps:
            # resilience boundary: chaos fault injection (no-op without an
            # active plan), then the preemption check — a pending request
            # flushes the final checkpoint through state_hook and raises
            chaos = active_chaos()
            if chaos is not None:
                try:
                    trainables = chaos.on_train_boundary(
                        "adam", epoch0 + cur_epochs, trainables)
                except Exception:
                    if pbar is not None:
                        pbar.close()
                    raise
            if preemption_requested():
                t_flush = time.perf_counter()
                if state_hook is not None:
                    state_hook(trainables, opt_state, cur_epochs,
                               best=(best[0], best[1],
                                     int(best[2]) // max(n_batches, 1)))
                flush_s = time.perf_counter() - t_flush
                note_final_flush("adam", epoch0 + cur_epochs, flush_s,
                                 verbose=verbose)
                if pbar is not None:
                    pbar.close()
                raise Preempted("adam", epoch0 + cur_epochs,
                                flush_s=(flush_s if state_hook is not None
                                         else None))
    if pbar is not None:
        pbar.close()
    # tdq: allow[host-sync-in-hot-path] phase-final fence: the wall clock must include the last chunk's device time
    jax.block_until_ready(trainables)
    result.wall_time["adam"] = time.time() - t0

    best_params, best_loss, best_step = best
    result.best_params["adam"] = tree_copy(best_params)
    result.min_loss["adam"] = float(best_loss)
    result.best_epoch["adam"] = int(best_step) // max(n_batches, 1)
    return trainables, opt_state, result
