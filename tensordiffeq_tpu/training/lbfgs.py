"""On-device L-BFGS refinement.

The reference does second-order refinement two ways: a hand-written eager
L-BFGS with two-loop recursion driven from Python (``optimizers.py:107-313``,
the default, ``fit.py:60-89``) and a tfp graph-mode variant
(``optimizers.py:11-104``).  Both pay a host round-trip per iteration.

Here the entire optimization — two-loop recursion (via optax's compact-form
``scale_by_lbfgs``), zoom line search satisfying strong Wolfe conditions, and
the iteration loop itself — runs inside ONE jitted ``lax.scan`` chunk on
device.  The host only sees loss telemetry every ``chunk`` iterations and
applies the reference's NaN/convergence stops between chunks
(``optimizers.py:273,290-291`` — including fixing the reference's broken
``tf.abs(f, f_old)`` convergence test, SURVEY §2.4.5).

L-BFGS optimizes the network parameters only; SA λ stay frozen — matching the
reference, whose flat-gradient closure covers ``u_model.trainable_variables``
alone (``models.py:283-295``).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..resilience.cluster import beat
from ..resilience.preemption import (Preempted, note_final_flush,
                                     preemption_requested)
from ..telemetry import log_event
from ..utils import tree_copy
from .progress import progress_bar

# ``optax.tree`` is the >=0.2.4 alias of ``optax.tree_utils``; 0.2.3 (the
# floor this repo supports) only ships the long name, and the two entry
# points we use are spelled differently there (``tree_get``/``tree_l2_norm``)
_optax_tree = getattr(optax, "tree", None)
_tree_get = (_optax_tree.get if _optax_tree is not None
             else optax.tree_utils.tree_get)
_tree_norm = (_optax_tree.norm if _optax_tree is not None
              else optax.tree_utils.tree_l2_norm)


def _log_stop(msg: str, **fields) -> None:
    """Early-stop diagnostics print to stderr unconditionally: a silent stop
    inside a long benchmark run is indistinguishable from a completed phase
    in the artifact (the 2026-08-01 north-star TPU capture lost its L-BFGS
    phase to an unexplained sub-1000-iter stop precisely because this was
    gated on ``verbose``).  stderr, not stdout — bench workers speak
    JSON-line protocol on stdout; ``log_event`` routes warnings there and
    mirrors the stop into any active telemetry run log."""
    log_event("l-bfgs", msg, level="warning", **fields)


def lbfgs_minimize(fun: Callable, x0, maxiter: int = 1000,
                   memory_size: int = 50, tol_fun: float = 1e-12,
                   tol_grad: float = 1e-12, chunk: int = 100,
                   verbose: bool = False, eager: bool = False,
                   learning_rate: float = 0.8,
                   callback: Optional[Callable] = None,
                   callback_every: int = 0, args: tuple = (),
                   telemetry=None, iter0: int = 0,
                   preempt_flush: Optional[Callable] = None,
                   fun_fallback: Optional[Callable] = None):
    """Minimise ``fun(pytree, *args) -> scalar`` with jitted L-BFGS.

    Returns ``(x_final, x_best, f_best, best_iter, history)`` where
    ``history`` is the per-iteration loss as a Python list.  Defaults mirror
    the reference's eager L-BFGS (50 correction pairs, ``tolFun=1e-12``,
    ``optimizers.py:114-116``) with a strong-Wolfe zoom line search in place
    of its fixed 0.8 learning rate; ``eager=True`` keeps the reference's
    fixed-step rule (``lr=0.8``, ``optimizers.py:114``) for dynamics parity.

    ``args`` (problem data: collocation points, frozen λ) are threaded into
    the jitted chunk as traced inputs, NOT closed over — closing over a
    global sharded array is illegal under multi-host
    (``jax.distributed``-initialized) execution, where each process only
    addresses its own shard.

    ``telemetry``: optional
    :class:`~tensordiffeq_tpu.telemetry.TrainingTelemetry` — records the
    per-chunk dispatch/device step-time split (``block_until_ready``
    fenced), same contract as the Adam loop's.

    ``iter0`` / ``preempt_flush``: the preemption contract (mirrors the
    Adam loop's ``epoch0``/``state_hook``): a pending preemption request is
    noticed at the next chunk boundary, ``preempt_flush(done, x, best)``
    writes the final checkpoint UNCONDITIONALLY (the cadence-gated
    ``callback`` may have skipped this boundary), and
    :class:`~tensordiffeq_tpu.resilience.Preempted` is raised with the
    absolute iteration ``iter0 + done``.

    ``fun_fallback``: the automatic precision retreat.  When set, ``fun``
    is treated as a reduced-precision objective (the bf16 fused minimax
    loss): a NaN stop or a ``tol_fun`` stagnation stop with budget
    remaining — the two faces of a Wolfe line search drowning in bf16
    gradient noise (PERF.md) — switches the remaining iterations to
    ``fun_fallback`` (full precision), restarting the curvature memory
    (bf16-era pairs mis-scale the f32 landscape) from the best finite
    iterate so far.  The retreat happens at most once; genuine
    convergence (gradient-norm stop) never triggers it.
    """
    def make_runner(fn):
        if eager:
            opt = optax.lbfgs(learning_rate=learning_rate,
                              memory_size=memory_size, linesearch=None)
        else:
            opt = optax.lbfgs(
                memory_size=memory_size,
                linesearch=optax.scale_by_zoom_linesearch(
                    max_linesearch_steps=30))

        @partial(jax.jit, static_argnames=("n_steps",),
                 donate_argnums=(0, 1, 2))
        def run_chunk(x, state, best, it0, fn_args, n_steps: int):
            # bind the traced data refs: a closure over *tracers* is fine,
            # it is the device-array closure that breaks multi-host
            def fun_local(p):
                return fn(p, *fn_args)

            if eager:
                plain_vg = jax.value_and_grad(fun_local)

                def value_and_grad(x, state):
                    return plain_vg(x)
            else:
                value_and_grad = optax.value_and_grad_from_state(fun_local)

            def step(carry, i):
                x, state, best = carry
                value, grad = value_and_grad(x, state=state)
                updates, state = opt.update(grad, state, x, value=value,
                                            grad=grad, value_fn=fun_local)
                x_new = optax.apply_updates(x, updates)
                if eager:
                    # no line-search state to read the post-step value
                    # from; track best at the iterate we just evaluated
                    new_value, x_at = value, x
                else:
                    new_value = _tree_get(state, "value")
                    x_at = x_new
                x = x_new

                x_best, f_best, i_best = best
                # guard: never adopt a NaN/inf iterate as "best"
                improved = jnp.isfinite(new_value) & (new_value < f_best)
                best = (
                    jax.tree_util.tree_map(
                        lambda new, old: jnp.where(improved, new, old),
                        x_at, x_best),
                    jnp.where(improved, new_value, f_best),
                    jnp.where(improved, it0 + i, i_best),
                )
                gnorm = _tree_norm(grad)
                return (x, state, best), (new_value, gnorm)

            (x, state, best), (values, gnorms) = jax.lax.scan(
                step, (x, state, best), jnp.arange(n_steps))
            return x, state, best, values, gnorms

        return opt, run_chunk

    opt, run_chunk = make_runner(fun)
    # copies: run_chunk donates its carried state, so the caller's x0 (the
    # solver's params) must stay valid — and opt.init's state aliases the
    # params buffers, which donation forbids (double-donate), so the state
    # is copied to distinct buffers too
    x = tree_copy(x0)
    state = tree_copy(opt.init(x))
    best = (tree_copy(x0), jnp.asarray(jnp.inf), jnp.asarray(-1))
    history: list[float] = []
    f_prev = np.inf
    done = 0
    retreated = fun_fallback is None
    pbar = progress_bar(maxiter, desc="L-BFGS") if verbose else None
    while done < maxiter:
        n = int(min(chunk, maxiter - done))
        t_chunk0 = time.perf_counter()
        x, state, best, values, gnorms = run_chunk(
            x, state, best, jnp.asarray(done), args, n)
        if telemetry is not None:
            t_disp = time.perf_counter() - t_chunk0
            # tdq: allow[host-sync-in-hot-path] fenced telemetry point: the deliberate per-chunk dispatch/device split fence
            jax.block_until_ready(values)
            telemetry.on_step_time(
                "l-bfgs", n, t_disp,
                time.perf_counter() - t_chunk0 - t_disp)
        # tdq: allow[host-sync-in-hot-path] per-chunk history transfer: the stop tests need host values once per chunk
        values = np.asarray(values)
        # tdq: allow[host-sync-in-hot-path] rides the same per-chunk transfer as values
        gnorms = np.asarray(gnorms)
        history.extend(float(v) for v in values)
        prev_done = done
        done += n
        # cluster heartbeat (no-op without a supervisor): the np.asarray
        # above fenced the device, so this certifies forward progress
        beat("l-bfgs", iter0 + done)
        if (callback is not None and callback_every > 0
                and prev_done // callback_every != done // callback_every):
            # the live running best rides along so mid-run checkpoints can
            # carry the best iterate (not just the latest one)
            callback(done, x, best)
        if preemption_requested() and done < maxiter:
            t_flush = time.perf_counter()
            if preempt_flush is not None:
                preempt_flush(done, x, best)
            flush_s = time.perf_counter() - t_flush
            note_final_flush("l-bfgs", iter0 + done, flush_s,
                             verbose=verbose)
            if pbar is not None:
                pbar.close()
            raise Preempted("l-bfgs", iter0 + done,
                            flush_s=(flush_s if preempt_flush is not None
                                     else None))
        if pbar is not None:
            pbar.update(n)
            pbar.set_postfix(loss=float(values[-1]))
        f_now = float(values[-1])
        stop = None
        if not np.isfinite(f_now):  # NaN stop (reference optimizers.py:290-291)
            stop = "non-finite"
        elif abs(f_prev - f_now) < tol_fun:
            stop = "stagnation"
        if stop is not None and not retreated and done < maxiter:
            # precision retreat: the reduced-precision objective stalled
            # (or blew up) with budget left — finish on the full-precision
            # one.  Curvature memory restarts: bf16-era pairs mis-scale
            # the f32 landscape.  Resume from the best finite iterate.
            retreated = True
            _log_stop(f"{stop} on the reduced-precision loss at iter "
                      f"{done}; retreating to the full-precision engine "
                      f"for the remaining {maxiter - done} iters")
            x_best, _, i_best = best
            # x_best is ALWAYS the safe restart point: the best finite
            # iterate, or the caller's initial params when nothing ever
            # improved (a NaN first chunk) — never restart the f32 phase
            # from a possibly-poisoned last iterate
            x = tree_copy(x_best)
            opt, run_chunk = make_runner(fun_fallback)
            state = tree_copy(opt.init(x))
            # re-measure the incumbent under the full-precision objective:
            # a bf16-measured f_best can under-read by the engine's
            # crosscheck band (~5e-2 rel) and veto genuinely better f32
            # iterates in the improved-guard below
            best = (x_best, jnp.asarray(fun_fallback(x_best, *args)),
                    i_best)
            f_prev = np.inf
            continue
        if stop == "non-finite":
            _log_stop(f"non-finite loss at iter {done} — "
                      "stopping, keeping best iterate")
            break
        if stop == "stagnation":
            _log_stop(f"tolerance stop at iter {done}: "
                      f"|f_prev-f_now|={abs(f_prev - f_now):.3e} < "
                      f"tol_fun={tol_fun:g} (f={f_now:.6e})")
            break
        if float(gnorms[-1]) < tol_grad:
            _log_stop(f"gradient stop at iter {done}: "
                      f"|g|={float(gnorms[-1]):.3e} < tol_grad={tol_grad:g}")
            break
        f_prev = f_now
    if pbar is not None:
        pbar.close()

    x_best, f_best, i_best = best
    return x, x_best, f_best, i_best, history


def fit_lbfgs(loss_fn: Callable, params, lambdas, X_f,
              maxiter: int = 1000, memory_size: int = 50,
              verbose: bool = True, chunk: int = 100, eager: bool = False,
              callback: Optional[Callable] = None,
              callback_every: int = 0, telemetry=None, iter0: int = 0,
              preempt_flush: Optional[Callable] = None,
              loss_fn_fallback: Optional[Callable] = None):
    """L-BFGS phase over network params with SA λ frozen
    (reference ``fit.py:60-89``).

    ``loss_fn_fallback``: full-precision objective for the automatic
    retreat when ``loss_fn`` is a reduced-precision (bf16) engine and its
    line search stagnates — see :func:`lbfgs_minimize`.

    Returns ``(params_final, params_best, best_loss, best_iter, loss_dicts)``
    with ``loss_dicts`` shaped like the Adam history entries."""
    lam_bcs = lambdas["BCs"]
    lam_res = lambdas["residual"]
    lam_data = lambdas.get("data", (None,))[0]

    # data rides `args` (traced chunk inputs), never a closure: required for
    # multi-host, where X_f/λ span devices this process cannot address
    def fun(p, lam_bcs, lam_res, X_f, lam_data):
        return loss_fn(p, lam_bcs, lam_res, X_f, lam_data=lam_data)[0]

    fun_fallback = None
    if loss_fn_fallback is not None and loss_fn_fallback is not loss_fn:
        def fun_fallback(p, lam_bcs, lam_res, X_f, lam_data):
            return loss_fn_fallback(p, lam_bcs, lam_res, X_f,
                                    lam_data=lam_data)[0]

    t0 = time.time()
    x, x_best, f_best, i_best, history = lbfgs_minimize(
        fun, params, maxiter=maxiter, memory_size=memory_size,
        chunk=chunk, verbose=verbose, eager=eager,
        callback=callback, callback_every=callback_every,
        args=(lam_bcs, lam_res, X_f, lam_data), telemetry=telemetry,
        iter0=iter0, preempt_flush=preempt_flush,
        fun_fallback=fun_fallback)
    log_event("l-bfgs",
              f"{len(history)} iters in {time.time() - t0:.1f}s, "
              f"best loss {float(f_best):.3e} @ iter {int(i_best)}",
              verbose=verbose)
    loss_dicts = [{"Total Loss": v} for v in history]
    return x, tree_copy(x_best), f_best, i_best, loss_dicts
