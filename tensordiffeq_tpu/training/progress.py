"""Progress reporting with a tqdm-free fallback."""

from __future__ import annotations


class _PlainBar:
    """Minimal stand-in for ``tqdm.trange`` when tqdm is unavailable:
    accepts the same calls, prints a line every update."""

    def __init__(self, total: int, desc: str = ""):
        self.total = total
        self.desc = desc
        self.n = 0
        self._postfix = ""

    def update(self, n: int = 1):
        self.n += n
        print(f"{self.desc}: {self.n}/{self.total} {self._postfix}", flush=True)

    def set_postfix(self, **kwargs):
        self._postfix = " ".join(f"{k}={v}" for k, v in kwargs.items())

    def close(self):
        pass


def progress_bar(total: int, desc: str = ""):
    try:
        from tqdm.auto import trange
        return trange(total, desc=desc)
    except Exception:  # pragma: no cover
        return _PlainBar(total, desc)
