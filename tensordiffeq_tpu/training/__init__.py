"""Training engines: jitted Adam(+SA) scan loops and on-device L-BFGS."""

from .fit import FitResult, fit_adam, make_optimizer  # noqa: F401
from .lbfgs import fit_lbfgs, lbfgs_minimize  # noqa: F401
