"""Inverse-problem solver: learn PDE coefficients from observed data.

TPU-native counterpart of the reference ``DiscoveryModel``
(``models.py:324-398``).  The reference juggles three Adam optimizers and
fragile gradient-list slicing (``grads[-(len_+1)]`` index arithmetic —
SURVEY §2.4.9); here the unknowns are just extra leaves of one trainable
pytree ``{"params", "vars", "col_weights"}`` routed through a single
``optax.multi_transform``: Adam on the network, Adam on the coefficients,
Adam-*ascent* on the SA collocation weights (the ``-grads`` minimax of
reference ``models.py:369``).

First-class like the forward solver (round-2 promotion):

* ``fused=`` — the residual can run on the stacked Taylor-propagation engine
  (:mod:`..ops.fused`); the trainable coefficients ride through the batched
  ``f_model`` re-run as traced scalars, and the engine is numerically
  cross-checked against the generic per-point autodiff before adoption.
* ``dist=`` — observation rows (``X``, ``u``, SA ``col_weights``) shard over
  the ``"data"`` mesh axis; params and coefficients replicate; XLA inserts
  the ICI all-reduces for the loss means.
* ``save_checkpoint``/``restore_checkpoint`` — full state (net params,
  coefficients, SA weights, Adam moments, histories) round-trips.

User contract (JAX-style, per-point)::

    def f_model(u, var, x, t):
        c1, c2 = var
        u_xx = grad(grad(u, "x"), "x")
        return grad(u, "t")(x, t) - c1 * u_xx(x, t) + c2 * u(x, t)**3

against observations ``u`` at points ``X`` (reference example:
``examples/AC-discovery.py:18-26``).  The SA residual weighting defaults
to the reference's ``g(λ)=λ²`` (``models.py:348``); ``compile(g=...)``
overrides it (e.g. a bounded transform against λ runaway on long runs).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..networks import neural_net
from ..ops.derivatives import make_ufn, vmap_residual
from .collocation import NotCompiledError
from ..ops.losses import MSE, default_g, g_MSE
from ..output import print_screen
from ..training.fit import make_batches
from ..training.progress import progress_bar


class DiscoveryModel:
    """Learn PDE coefficients ``var`` jointly with the solution network."""

    def compile(self, layer_sizes: Sequence[int], f_model: Callable, X, u,
                var: Sequence[float], col_weights=None,
                varnames: Optional[Sequence[str]] = None,
                lr: "float | Callable" = 0.005,
                lr_vars=0.005,
                lr_weights: "float | Callable" = 0.005,
                seed: int = 0, verbose: bool = True,
                fused: Optional[bool] = None, dist: bool = False,
                network=None, g: Optional[Callable] = None):
        """Assemble the inverse problem (reference ``models.py:325-341``).

        Args:
          layer_sizes: MLP sizes ``[n_in, …, n_out]``.
          f_model: per-point residual ``f_model(u, var, *coords)``.
          X: observation coordinates — ``[n, d]`` array or list of ``d``
            column vectors (the reference passes a column list,
            ``examples/AC-discovery.py:51``).
          u: observed solution values ``[n, n_out]``.
          var: initial guesses for the unknown coefficients.
          lr_vars: coefficient learning rate — one float (or optax
            schedule) shared by all coefficients, or a sequence with one
            per coefficient for problems whose coefficients live at very
            different scales (see the per-var note in the source).
          col_weights: optional SA collocation weights ``[n, 1]`` (λ², with
            gradient ascent — reference ``models.py:348,369``).
          g: optional λ transform replacing the reference's fixed
            ``g(λ)=λ²`` (``models.py:348``).  Beyond-reference: a BOUNDED
            transform (e.g. ``lambda l: jnp.tanh(l) ** 2``) tames the λ
            runaway measured on long SA runs, where unbounded ascent
            degrades the u-fit and biases the recovered coefficients
            (CONVERGENCE.md, AC discovery per-var-lr rows).
          varnames: coordinate names for ``grad(u, "x")`` style authoring
            (defaults to ``x0, x1, …``).
          fused: residual engine selection, as on the forward solver —
            ``None`` auto (with numeric cross-check + silent fallback),
            ``False`` generic, ``True`` require fusion.
          dist: shard observation rows (and SA col_weights) over all local
            devices; coefficients and network replicate.
          network: optional custom Flax module replacing the default MLP.
        """
        from ..utils import enable_compilation_cache
        enable_compilation_cache()  # warm process starts skip XLA compiles
        if isinstance(X, (list, tuple)):
            X = np.hstack([np.reshape(c, (-1, 1)) for c in X])
        self.X = jnp.asarray(X, jnp.float32)
        self.ndim = int(self.X.shape[1])
        self.u_data = jnp.asarray(np.reshape(u, (self.X.shape[0], -1)),
                                  jnp.float32)
        self.layer_sizes = list(layer_sizes)
        self.n_out = int(layer_sizes[-1])
        self.f_model = f_model
        self.varnames = tuple(varnames) if varnames is not None else tuple(
            f"x{i}" for i in range(self.ndim))
        if len(self.varnames) != self.ndim:
            raise ValueError(
                f"X has {self.ndim} coordinate column(s) but varnames names "
                f"{len(self.varnames)}: {self.varnames}")
        self.verbose = verbose
        self.fused = fused
        self.dist = dist
        self.g = g

        self.net = network if network is not None else neural_net(layer_sizes)
        self.params = self.net.init(jax.random.PRNGKey(seed),
                                    jnp.zeros((1, self.ndim), jnp.float32))
        self.apply_fn = self.net.apply

        self.trainables = {
            "params": self.params,
            "vars": [jnp.asarray(v, jnp.float32) for v in var],
            "col_weights": (None if col_weights is None
                            else jnp.asarray(col_weights, jnp.float32)),
        }

        if self.dist:
            self._shard_observations()

        # lr_vars: one float/schedule for every coefficient, or a sequence
        # with one entry per coefficient.  Per-var rates matter because
        # Adam normalizes gradient MAGNITUDE but not loss CURVATURE: for
        # Allen-Cahn discovery ∂f/∂c1 = -u_xx is ~1e4 larger than
        # ∂f/∂c2 = u³-u, and a single rate big enough to carry c2 to 5.0
        # parks c1 (true value 1e-4) at a ~lr-sized noise floor 10-100x
        # its target.  The reference's one-Adam-for-all-vars design
        # (``models.py:335,370``) cannot express this.
        if getattr(lr_vars, "ndim", 0) > 0:  # array of rates == sequence
            lr_vars = [float(v) for v in np.asarray(lr_vars)]
        per_var = isinstance(lr_vars, (list, tuple))
        if per_var and len(lr_vars) != len(self.trainables["vars"]):
            raise ValueError(
                f"lr_vars has {len(lr_vars)} entries for "
                f"{len(self.trainables['vars'])} coefficients")

        def label_fn(tr):
            vlab = ([f"var{i}" for i in range(len(tr["vars"]))] if per_var
                    else jax.tree_util.tree_map(lambda _: "vars", tr["vars"]))
            return {"params": jax.tree_util.tree_map(lambda _: "net", tr["params"]),
                    "vars": vlab,
                    "col_weights": jax.tree_util.tree_map(lambda _: "lam",
                                                          tr["col_weights"])}

        transforms = {"net": optax.adam(lr, b1=0.99),
                      "lam": optax.chain(optax.scale(-1.0),
                                         optax.adam(lr_weights, b1=0.99))}
        if per_var:
            transforms.update({f"var{i}": optax.adam(lv, b1=0.99)
                               for i, lv in enumerate(lr_vars)})
        else:
            transforms["vars"] = optax.adam(lr_vars, b1=0.99)
        self.opt = optax.multi_transform(transforms, label_fn)
        self.opt_state = self.opt.init(self.trainables)
        self.losses: list[float] = []
        self.var_history: list[list[float]] = []
        self._build()
        return self

    # ------------------------------------------------------------------ #
    def _shard_observations(self):
        """Place observation rows (and SA col_weights) over the "data" mesh
        axis — data parallelism over the observation/collocation set, the
        same layout as the forward solver's dist path."""
        from ..parallel import data_sharding, make_mesh, replicated
        mesh = make_mesh()
        n_dev = int(np.prod(mesh.devices.shape))
        n = int(self.X.shape[0])
        keep = n - n % n_dev
        if keep != n:
            from ..telemetry import log_event
            log_event("discovery", f"trimming observations {n} -> {keep} "
                      f"to tile {n_dev} devices", verbose=self.verbose)
        self.X = jax.device_put(self.X[:keep], data_sharding(mesh, 2))
        self.u_data = jax.device_put(self.u_data[:keep],
                                     data_sharding(mesh, 2))
        cw = self.trainables["col_weights"]
        if cw is not None:
            self.trainables["col_weights"] = jax.device_put(
                cw[:keep], data_sharding(mesh, cw.ndim))
        self.trainables["vars"] = [jax.device_put(v, replicated(mesh))
                                   for v in self.trainables["vars"]]

    # ------------------------------------------------------------------ #
    def _try_fuse(self):
        """Mirror of the forward solver's engine selection for the
        ``f_model(u, var, *coords)`` contract."""
        from ..ops.fused import analyze_f_model, make_fused_residual, \
            mlp_qualifies

        self._fuse_fail_reason = None
        if mlp_qualifies(self.net, self.params) is None:
            return None
        var_dummies = [np.float32(np.asarray(v))
                       for v in self.trainables["vars"]]
        requests, reason = analyze_f_model(
            self.f_model, self.varnames, self.n_out, return_reason=True,
            prefix_args=(var_dummies,))
        if requests is None:
            self._fuse_fail_reason = reason
            return None
        # return_primal: the data loss evaluates at the same X the residual
        # does, so u(X) rides the Taylor table — no second network forward
        return make_fused_residual(self.f_model, self.varnames, self.n_out,
                                   requests, precision=self.net.precision,
                                   has_prefix_arg=True, return_primal=True)

    def _generic_residual(self, params, vars_, X):
        """The one generic (autodiff) construction of ``f_model(u, var, ·)``
        — serves training's fallback path, the fused cross-check, and
        :meth:`predict_f`, so the residual they evaluate can never drift
        apart."""
        u = make_ufn(self.apply_fn, params, self.varnames, self.n_out)
        return vmap_residual(
            lambda u_, *coords: self.f_model(u_, vars_, *coords),
            u, self.ndim)(X)

    def _crosscheck_fused(self, n_check: int = 32):
        from ..ops.fused import crosscheck_residuals

        X_s = self.X[: min(n_check, int(self.X.shape[0]))]
        vars0 = self.trainables["vars"]
        generic = self._generic_residual(self.params, vars0, X_s)
        try:
            fused, u_primal = self._fused_residual(self.params, X_s, vars0)
        except Exception as e:
            return False, e
        ok, reason = crosscheck_residuals(generic, fused)
        if not ok:
            return ok, reason
        # the Data loss consumes the table's primal channel — validate it
        # against apply_fn too (an f_model that never evaluates u itself
        # would otherwise leave this path completely unchecked)
        return crosscheck_residuals(self.apply_fn(self.params, X_s),
                                    u_primal)

    # ------------------------------------------------------------------ #
    def _build(self, batch_sz=None):
        X, u_data = self.X, self.u_data
        apply_fn = self.apply_fn
        generic_residual = self._generic_residual
        g_fn = self.g if self.g is not None else default_g
        self._built_batch = batch_sz

        self._fused_residual = self._try_fuse() if self.fused is not False \
            else None
        if self.fused is True and self._fused_residual is None:
            reason = getattr(self, "_fuse_fail_reason", None)
            msg = ("fused=True but the discovery residual cannot be fused "
                   "(requires the standard float32 tanh MLP and grad() "
                   "combinators on untransformed coordinates)")
            if reason is not None:
                raise ValueError(f"{msg}; analysis stopped on: "
                                 f"{type(reason).__name__}: {reason}") \
                    from reason
            raise ValueError(msg)
        if self._fused_residual is not None:
            ok, reason = self._crosscheck_fused()
            if not ok:
                if self.fused is True:
                    raise ValueError(
                        "fused discovery residual failed the numeric "
                        "cross-check") from reason
                self._fuse_fail_reason = reason
                self._fused_residual = None
                from ..telemetry import log_event
                log_event("fuse", f"discovery cross-check failed "
                          f"({type(reason).__name__}); using the generic "
                          "engine", verbose=self.verbose, level="warning")
        fused_res = self._fused_residual

        # minibatching (round 4): the reference trains the inverse problem
        # full-batch only; batch_sz slices the observation rows so the full
        # 512x201 reference grid (~103k rows) trains at a bounded per-step
        # cost.  Per-row SA col_weights are gathered alongside their batch
        # rows; only those rows receive a gradient each step (out-of-batch
        # rows still drift on decayed Adam moments between their turns —
        # the same semantics as the forward solver's minibatch+SA path).
        # Both layouts use make_batches' ceil-batching with wraparound, so
        # NO row is ever dropped (the tail batch wraps), with permute=True:
        # batches are PERMUTED subsets, not contiguous row blocks —
        # observation grids come meshgrid-ordered (x-major), so a
        # contiguous batch is a thin x-slab of the domain, measured on the
        # 512x201 AC grid to destabilise the coefficients (spatially
        # biased gradients oscillated c2 from 3.1 back to 1.6 over one
        # leg).  The fixed seeded shuffle makes every batch domain-covering
        # and deterministic, so batches replay identically across fit
        # calls and checkpoint resumes (under dist the shuffle is within
        # each device's block, keeping the λ gather device-local).
        mesh = None
        if self.dist:
            from ..parallel import make_mesh
            mesh = make_mesh()
        X_batched, idx_batched, n_batches = make_batches(
            X, batch_sz, mesh=mesh, verbose=self.verbose, permute=True)
        self._batch_idx = idx_batched  # introspection/tests
        self._n_batches = n_batches

        def loss_parts(tr, X_b, u_b, cw_b):
            if fused_res is not None:
                # primal u(X) comes out of the same Taylor propagation the
                # residual uses — one network traversal serves both losses
                f_pred, u_pred = fused_res(tr["params"], X_b, tr["vars"])
            else:
                u_pred = apply_fn(tr["params"], X_b)
                f_pred = generic_residual(tr["params"], tr["vars"], X_b)
            preds = f_pred if isinstance(f_pred, tuple) else (f_pred,)
            data_loss = MSE(u_pred, u_b)
            comps = {"Data": data_loss}
            res_loss = 0.0
            for i, p in enumerate(preds):
                p = p.reshape(-1, 1)
                if cw_b is not None:
                    term = g_MSE(p, 0.0, g_fn(cw_b))
                else:
                    term = MSE(p, 0.0)
                comps[f"Residual_{i}" if len(preds) > 1 else "Residual"] = term
                res_loss = res_loss + term
            return data_loss + res_loss, comps

        def loss_fn(tr):
            """Full-set loss (public contract; also the eval/cross-check
            path) — identical maths to the batched training loss."""
            return loss_parts(tr, X, u_data, tr["col_weights"])

        def loss_batch(tr, X_b, idx_b):
            if n_batches == 1:
                return loss_parts(tr, X, u_data, tr["col_weights"])
            cw = tr["col_weights"]
            return loss_parts(tr, X_b, u_data[idx_b],
                              None if cw is None else cw[idx_b])

        grad_fn = jax.value_and_grad(loss_batch, has_aux=True)
        opt = self.opt

        @partial(jax.jit, static_argnames=("n_steps",))
        def run_chunk(trainables, opt_state, step0, n_steps: int):
            def step(carry, i):
                trainables, opt_state = carry
                b = (step0 + i) % n_batches
                X_b = X_batched[b] if n_batches > 1 else X_batched[0]
                idx_b = idx_batched[b] if n_batches > 1 else idx_batched[0]
                (total, _), grads = grad_fn(trainables, X_b, idx_b)
                updates, opt_state = opt.update(grads, opt_state, trainables)
                trainables = optax.apply_updates(trainables, updates)
                return (trainables, opt_state), (total,
                                                 [v for v in trainables["vars"]])

            (trainables, opt_state), (losses, var_hist) = jax.lax.scan(
                step, (trainables, opt_state), jnp.arange(n_steps))
            return trainables, opt_state, losses, var_hist

        self._run_chunk = run_chunk
        self.loss_fn = loss_fn

    # ------------------------------------------------------------------ #
    @property
    def vars(self) -> list[np.ndarray]:
        """Current coefficient estimates."""
        return [np.asarray(v) for v in self.trainables["vars"]]

    @property
    def col_weights(self):
        cw = self.trainables["col_weights"]
        return None if cw is None else np.asarray(cw)

    def fit(self, tf_iter: int, chunk: int = 100,
            batch_sz: Optional[int] = None):
        """Joint Adam training loop (reference ``models.py:381-398``).

        ``batch_sz`` (beyond-reference) minibatches the observation rows:
        ``tf_iter`` counts **epochs** — every batch trains each epoch
        (``tf_iter × ceil(n/batch_sz)`` optimizer steps), the same
        contract as the forward solver's
        :func:`~tensordiffeq_tpu.training.fit.fit_adam`.  (Until round 8
        it counted raw steps, which silently trained ``n_batches``×
        fewer sweeps than the same ``tf_iter`` full-batch — the root
        cause of the long-standing minibatch-discovery tier-1 failure:
        400 "iterations" at 4 batches were only 100 sweeps, inside the
        coefficient's identification noise floor.  CONVERGENCE.md
        records the re-derived gate.)  Each step trains one fixed
        PERMUTED subset of rows (observation grids are meshgrid-ordered,
        and contiguous slabs were measured to destabilise the
        coefficients — see ``_build``), rotating with a wraparound tail
        batch so every row trains every sweep (under ``dist`` the
        permutation is within each device's block, keeping the λ gather
        local).  Per-row SA ``col_weights`` ride with their rows — note
        that between a row's turns its λ still drifts on decayed Adam
        moments (standard sparse-gradient Adam; a bounded ``g=``
        transform caps the loss-side effect).  ``losses`` and
        ``var_history`` record one entry per epoch (the epoch's last
        batch), and batches rotate continuously across ``fit`` calls and
        checkpoint resumes (the epoch counter persists via the loss
        history)."""
        self.train_loop(tf_iter, chunk=chunk, batch_sz=batch_sz)
        return self

    def train_loop(self, tf_iter: int, chunk: int = 100,
                   batch_sz: Optional[int] = None):
        if getattr(self, "_built_batch", None) != batch_sz:
            self._build(batch_sz)  # re-jit only when the batch layout changes
        if self.verbose:
            print_screen(self, discovery_model=True)
        t0 = time.time()
        n_batches = int(getattr(self, "_n_batches", 1))
        total_steps = tf_iter * n_batches
        epochs0 = len(self.losses)  # rotation resumes where the record ends
        pbar = progress_bar(tf_iter, desc="Discovery") if self.verbose else None
        steps_done = 0
        while steps_done < total_steps:
            n = int(min(chunk * n_batches, total_steps - steps_done))
            self.trainables, self.opt_state, losses, var_hist = self._run_chunk(
                self.trainables, self.opt_state,
                jnp.asarray(epochs0 * n_batches + steps_done, jnp.int32), n)
            losses = np.asarray(losses)
            stacked = [np.asarray(v) for v in var_hist]
            # one record per EPOCH (its last batch), matching fit_adam
            for e in range(n // n_batches):
                i = (e + 1) * n_batches - 1
                self.losses.append(float(losses[i]))
                self.var_history.append([float(v[i]) for v in stacked])
            steps_done += n
            if pbar is not None:
                pbar.update(n // n_batches)
                pbar.set_postfix(loss=self.losses[-1],
                                 vars=[round(v, 4) for v in self.var_history[-1]])
        if pbar is not None:
            pbar.close()
        self.wall_time = time.time() - t0

    # ------------------------------------------------------------------ #
    def save_checkpoint(self, path: str):
        """Full inverse-problem state: net params, coefficient estimates,
        SA col_weights, Adam moments, loss/coefficient histories."""
        from ..checkpoint import save_checkpoint
        state = {"trainables": self.trainables, "opt_state": self.opt_state}
        meta = {"losses": list(self.losses),
                "var_history": [list(v) for v in self.var_history]}
        save_checkpoint(path, state, meta)

    def restore_checkpoint(self, path: str):
        """Restore a :meth:`save_checkpoint` state into this (compiled)
        model; under ``dist=True`` the SA col_weights are re-placed on the
        mesh after loading."""
        if not hasattr(self, "trainables"):
            raise NotCompiledError(
                "Call compile(...) before restore_checkpoint")
        from ..checkpoint import restore_checkpoint
        template = {"trainables": self.trainables,
                    "opt_state": self.opt_state}
        state, meta = restore_checkpoint(path, template)
        self.trainables = state["trainables"]
        self.opt_state = state["opt_state"]
        self.losses = list(meta.get("losses", []))
        self.var_history = [list(v) for v in meta.get("var_history", [])]
        if self.dist:
            from ..parallel import data_sharding, make_mesh
            mesh = make_mesh()
            cw = self.trainables["col_weights"]
            if cw is not None:
                self.trainables["col_weights"] = jax.device_put(
                    jnp.asarray(cw), data_sharding(mesh, cw.ndim))
        return self

    # ------------------------------------------------------------------ #
    def export_surrogate(self):
        """Export the learned solution AND the learned PDE as a deployable
        :class:`~tensordiffeq_tpu.serving.Surrogate`: the current
        coefficient estimates are frozen into the artifact (persisted in
        its metadata), so a fresh-process restore —
        ``Surrogate.load(path, f_model=f_model)`` with the original
        ``f_model(u, var, *coords)`` — evaluates the learned equation's
        residual without any training state."""
        if not hasattr(self, "trainables"):
            raise NotCompiledError(
                "Call compile(...) before export_surrogate()")
        from ..serving import Surrogate
        return Surrogate.from_discovery(self)

    # ------------------------------------------------------------------ #
    def predict(self, X_star):
        X_star = jnp.asarray(X_star, jnp.float32)
        return np.asarray(self.apply_fn(self.trainables["params"], X_star))

    def predict_f(self, X_star):
        """Residual of the learned PDE at ``X_star`` under the CURRENT
        coefficient estimates — the load-and-evaluate flow of the
        reference's ``examples/AC-inference.py:18-26`` (build ``f_model``
        with tunable ``var``, then evaluate it on a restored model).
        Returns one ``[n, 1]`` array per residual equation."""
        X_star = jnp.asarray(X_star, jnp.float32)
        f = self._generic_residual(self.trainables["params"],
                                   self.trainables["vars"], X_star)
        if isinstance(f, tuple):
            return tuple(np.asarray(p).reshape(-1, 1) for p in f)
        return np.asarray(f).reshape(-1, 1)
