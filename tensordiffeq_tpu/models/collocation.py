"""The forward collocation solver.

TPU-native counterpart of the reference's ``CollocationSolverND``
(``tensordiffeq/models.py:12-322``): same user workflow —

    solver = CollocationSolverND()
    solver.compile(layer_sizes, f_model, domain, bcs, ...)
    solver.fit(tf_iter=10_000, newton_iter=10_000)
    u_pred, f_pred = solver.predict(X_star)

— but internally a thin stateful shell over pure jitted functions: the loss
is assembled once (:mod:`tensordiffeq_tpu.models.assembly`), training runs as
on-device ``lax.scan`` chunks (:mod:`tensordiffeq_tpu.training.fit`), and
L-BFGS refinement is a fully jitted ``lax.while_loop``
(:mod:`tensordiffeq_tpu.training.lbfgs`).  Distribution is data-parallel
SPMD: collocation points (and their SA λ) are sharded over a
:class:`jax.sharding.Mesh`; parameters are replicated; XLA inserts the ICI
collectives (:mod:`tensordiffeq_tpu.parallel`) — replacing the reference's
``MirroredStrategy`` scope dance (``models.py:235-277``).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import flax.serialization
import jax
import jax.numpy as jnp
import numpy as np

from ..boundaries import BC
from ..domains import DomainND
from ..networks import neural_net
from ..ops.derivatives import make_ufn, vmap_residual
from ..output import print_screen
from ..telemetry import as_training_telemetry, log_event
from ..training.fit import (FitResult, fit_adam, make_optimizer,
                            opt_state_matches)
from ..utils import initialize_lambdas, tree_copy
from .assembly import build_loss_fn


class NotCompiledError(RuntimeError):
    """A method that needs the compiled training graph ran before
    ``compile(...)`` (or ``load_model(...)`` where a loaded network
    suffices) — a usage-order error, typed so callers and the trace
    layer can dispatch on it instead of string-matching RuntimeError."""

    trace_id = None


class AutotuneFailure(RuntimeError):
    """``fused="autotune"`` had no surviving residual-engine candidate:
    every engine failed to even compile.  Carries ``failures`` (engine
    name -> exception) so the caller sees each candidate's reason."""

    trace_id = None

    def __init__(self, failures: dict):
        self.failures = dict(failures)
        super().__init__(
            "autotune: every residual engine candidate failed: "
            + "; ".join(f"{k}: {type(e).__name__}: {e}"
                        for k, e in failures.items()))


class _DeviceResampleHook:
    """``fit_adam``-facing adapter around
    :class:`~tensordiffeq_tpu.ops.resampling.DeviceResampler`: owns epoch
    re-basing (restored history + causal-stage offsets), keeps the
    solver's ``X_f`` in sync at swap time (the host mirror goes stale
    rather than paying a device→host pull per redraw), and prices the
    score pass once for the live cost model."""

    pipelined = True

    def __init__(self, solver, sampler, epoch_offset: int):
        self.solver = solver
        self.sampler = sampler
        self.epoch_offset = int(epoch_offset)
        self.stage_offset = 0
        self._flops = None

    def dispatch(self, params, X_cur, epoch: int):
        return self.sampler.redraw(
            params, X_cur, epoch + self.epoch_offset + self.stage_offset)

    def on_swap(self, X_new):
        s = self.solver
        s.X_f = X_new
        # stale marker: host-side consumers (NTK subsample, restore
        # templates) re-sync lazily via _sync_X_f_host()
        s._X_f_host = None

    def flops_info(self):
        """``(flops, basis)`` of one redraw's score+select program —
        credited to the overlapped chunk so ``cost.mfu`` stays honest.
        The analytic single-forward-pass floor substitutes when XLA's
        cost model is blinded (a pallas residual engine scores zero)."""
        if self._flops is None:
            from ..telemetry.costmodel import (analytic_mlp_flops,
                                               program_cost,
                                               resolve_flop_basis)
            s = self.solver
            n_pool = self.sampler.n_f + self.sampler.n_fresh
            floor = analytic_mlp_flops(s.layer_sizes, n_pool)
            measured = None
            try:
                measured = program_cost(
                    self.sampler.lower_redraw(s.params, s.X_f))["flops"]
            except Exception:
                pass
            self._flops = resolve_flop_basis(
                measured, floor,
                fallback=lambda: (floor, "analytic-resample"))
        return self._flops


class CollocationSolverND:
    """N-dimensional collocation PINN solver (forward problems).

    Reference parity: ``models.py:12-322``.  ``Adaptive_type`` keeps the
    reference's encoding (``models.py:35-39``): 0 = baseline, 1 =
    self-adaptive per-point (SA-PINN), 2 = self-adaptive scalar per-loss,
    3 = NTK balancing (declared but dead code in the reference,
    ``models.py:76-84``; actually implemented here —
    :mod:`tensordiffeq_tpu.ops.ntk`).
    """

    def __init__(self, assimilate: bool = False, verbose: bool = True,
                 seed: int = 0):
        self.assimilate = assimilate
        self.verbose = verbose
        self.seed = seed
        self.losses: list[dict] = []
        self.best_epoch = {"adam": -1, "l-bfgs": -1, "overall": -1}
        self.min_loss = {"adam": np.inf, "l-bfgs": np.inf, "overall": np.inf}
        self.best_model = {"adam": None, "l-bfgs": None, "overall": None}
        self.data_X = None
        self.data_s = None
        self.opt_state = None  # Adam moments; persists across fit() calls
        self._compiled = False

    # ------------------------------------------------------------------ #
    def compile(self, layer_sizes: Sequence[int], f_model: Callable,
                domain: DomainND, bcs: Sequence[BC], Adaptive_type: int = 0,
                dict_adaptive: Optional[dict] = None,
                init_weights: Optional[dict] = None,
                g: Optional[Callable] = None, dist: bool = False,
                network=None, lr: "float | Callable" = 0.005,
                lr_weights: "float | Callable" = 0.005,
                fused: Optional[bool] = None, fused_dtype=None,
                minimax: Optional[bool] = None,
                causal_eps=None, causal_bins: int = 32,
                causal_delta: float = 0.99,
                remat: bool = False, ntk_max_ratio: Optional[float] = 100.0,
                ntk_max_points: int = 256):
        """Assemble the problem (reference ``models.py:27-105``).

        Args:
          layer_sizes: ``[n_in, …, n_out]`` MLP sizes (or pass ``network``);
            ``None`` after :meth:`load_model` reuses the loaded architecture
            and parameters (transfer learning without re-stating the net).
          f_model: per-point residual ``f_model(u, *coords)`` written with
            :func:`tensordiffeq_tpu.grad` combinators.
          domain: :class:`DomainND` with collocation points generated.
          bcs: list of boundary/initial conditions.
          Adaptive_type: 0/1/2 as in the reference (``models.py:68-80``).
          dict_adaptive/init_weights: SA contract — which loss terms carry λ
            and their initial values (``models.py:40-42``).
          g: optional λ transform for residual terms (default ``None``).
          dist: shard collocation points (and per-point λ) over the data
            mesh (reference ``dist=True``, ``models.py:235``).  ``True``
            uses every global device (after
            :func:`~tensordiffeq_tpu.parallel.initialize_multihost` that
            spans hosts); an int takes the first that many devices; a
            device sequence is used as given — the handle elastic
            restores use to re-shard an 8-device checkpoint onto a
            4-device slice (see
            :func:`~tensordiffeq_tpu.parallel.resolve_mesh`).
          network: optional custom Flax module replacing the default MLP.
          fused: residual engine selection.  ``None`` (default) auto-uses the
            fused Taylor-propagation engine (:mod:`..ops.fused`) when
            ``f_model`` and the network qualify, falling back silently to
            per-point autodiff; ``False`` forces the generic engine;
            ``True`` requires fusion and raises if it isn't possible;
            ``"pallas"`` additionally requires the VMEM-resident pallas
            kernel table producer (:mod:`..ops.pallas_taylor`; runs in
            interpreter mode off-TPU); ``"autotune"`` compiles the candidate
            engines, times one full loss+grad step of each on the actual
            collocation set, and keeps the fastest (compile cost up front,
            best steady-state step guaranteed).
          fused_dtype: mixed-precision matmuls inside the fused Taylor
            engine (e.g. ``"bfloat16"``): matmul operands are cast down and
            accumulated in float32 — the MXU's native single-pass path —
            while every pointwise derivative chain stays float32.  An
            explicit accuracy/throughput trade-off (measure it with
            ``bench.py --precision``); the numeric cross-check runs with a
            correspondingly widened tolerance band.  Requires a fused
            engine (ignored with a warning for ``fused=False``).  L-BFGS
            refinement starts on the bf16 loss and automatically retreats
            to a full-precision engine when its Wolfe line search
            stagnates (the PERF.md-documented bf16 failure mode) — see
            :func:`~tensordiffeq_tpu.training.lbfgs.fit_lbfgs`.
          minimax: fused *minimax-step* engine selection
            (:mod:`..ops.pallas_minimax`).  ``None`` (default)
            auto-adopts, for the training loss, the fused unit that
            computes residual + SA-λ-weighted loss + parameter cotangents
            + the per-point, per-equation λ-ascent directions in one
            fusion (the VMEM-resident pallas kernel on real TPU, the
            fused-XLA jaxpr elsewhere) whenever the residual qualifies
            (fused engine active, single-column residual equations — a
            tuple-returning ``f_model`` adopts as an E-equation system
            with one λ/weight channel per component — no ``causal_eps``,
            no ``remat``) AND it passes the same numeric cross-check gate
            as the fused residual, run on the real (multi-component)
            collocation set; silently falls back otherwise.  ``False``
            forces the unfused loss; ``True`` requires the minimax engine
            and raises with the disqualifying reason.
          ntk_max_ratio: bound on the NTK weights' dynamic range
            (``Adaptive_type=3`` only): λ are clipped to ``ntk_max_ratio ×
            min(λ)``.  Default 100 — the raw paper formula was measured to
            under-weight a large-trace residual term ~4500× on Helmholtz,
            starving the PDE out of the gradient entirely (see
            ``ops/ntk.py``); ``None`` restores the unbounded formula.
          ntk_max_points: per-term trace subsample size for NTK weighting
            (``Adaptive_type=3`` only; default 256).  The traces set only
            the per-TERM balance, so a few hundred points estimate it
            stably at ``O(max_points × params)`` jacobian cost — the
            Helmholtz sensitivity runs at 512/1024 (CONVERGENCE.md,
            round 5) measure exactly this.
          remat: rematerialize the residual chain in the backward pass
            (``jax.checkpoint`` — see :func:`..models.assembly.
            build_loss_fn`): ~chain-multiplicity lower peak memory for one
            extra forward of FLOPs, the standard HBM lever for pushing
            ``N_f`` per chip (beyond-reference; the reference splits large
            ``N_f`` across GPUs instead, ``AC-dist-new.py:14``).
          causal_eps / causal_bins / causal_delta: temporal-causality
            weighting of the residual (Wang et al. arXiv:2203.07404,
            beyond-reference) — residual bin ``b`` along time is weighted
            ``exp(-causal_eps * cumulative earlier-bin loss)``, so later
            times train only once earlier times are resolved.  Composes
            with SA λ; per-epoch ``Causal_w_last_j`` in the loss history
            reports completeness (→1 when the whole horizon trains).
            A SEQUENCE of ε values enables the paper's annealing schedule
            (Algorithm 1): Adam starts at the smallest ε and advances to
            the next the moment the causal gate opens
            (``Causal_w_last > causal_delta``, checked at chunk
            boundaries), handing the remaining budget to the stricter
            stage — a fixed ε was measured to either never open the gate
            (large ε) or never enforce causality (small ε) at realistic
            budgets (``runs/weighting_ablation.json``).  Each stage
            re-jits once (the persistent compile cache absorbs repeats);
            a checkpoint-resumed fit restarts the ladder and fast-forwards
            through already-open stages at the first boundary check.
        """
        from ..utils import enable_compilation_cache
        enable_compilation_cache()  # warm process starts skip XLA compiles
        if domain.X_f is None:
            raise ValueError("Domain has no collocation points; call "
                             "domain.generate_collocation_points(N_f) first")
        if causal_eps is not None and domain.time_var is None:
            raise ValueError("causal_eps requires a domain with time_var "
                             "set (causality is ordered along time)")
        keep_params = False
        if layer_sizes is None:
            # transfer-learn flow: reuse the net+params brought in by
            # load_model on this (previously uncompiled) solver
            if not getattr(self, "_loaded", False):
                raise ValueError(
                    "layer_sizes=None requires load_model(path) first (the "
                    "architecture is then taken from the saved file)")
            layer_sizes = self.layer_sizes
            network = self.net if network is None else network
            keep_params = network is self.net
        self.layer_sizes = list(layer_sizes)
        self.domain = domain
        self.bcs = list(bcs)
        self.f_model = f_model
        self.g = g
        self.dist = dist
        self.fused = fused
        self.minimax = minimax
        # scalar -> single-stage ladder; sequence -> annealing schedule
        # (kept sorted ascending: the paper advances small -> large ε)
        if causal_eps is None:
            self.causal_ladder = []
        elif np.ndim(causal_eps) == 0:
            self.causal_ladder = [float(causal_eps)]
        else:
            self.causal_ladder = sorted(float(e) for e in causal_eps)
            if not self.causal_ladder:
                raise ValueError("causal_eps sequence must be non-empty")
        self.causal_eps = (self.causal_ladder[0]
                           if self.causal_ladder else None)
        self.causal_bins = causal_bins
        self.causal_delta = float(causal_delta)
        self.remat = remat
        self.ntk_max_ratio = ntk_max_ratio
        # trace subsample size (per term) for NTK weighting: the traces
        # drive only the per-TERM balance, so a few hundred points give a
        # stable estimate at O(max_points x params) jacobian cost; the
        # Helmholtz sensitivity runs (CONVERGENCE.md, round 5) measure the
        # 256 default against 512/1024
        self.ntk_max_points = int(ntk_max_points)
        self._causal_kw = {} if self.causal_eps is None else dict(
            causal_eps=self.causal_eps, causal_bins=causal_bins,
            time_index=domain.vars.index(domain.time_var),
            time_bounds=domain.bounds(domain.time_var))
        if fused_dtype is not None:
            if fused is False:
                import warnings
                warnings.warn("fused_dtype is ignored with fused=False "
                              "(the generic engine has no Taylor matmuls)")
                fused_dtype = None
            else:
                fused_dtype = jnp.dtype(fused_dtype).type
        self.fused_dtype = fused_dtype
        self.lr = lr
        self.lr_weights = lr_weights
        self.n_out = int(layer_sizes[-1])

        self.net = network if network is not None else neural_net(layer_sizes)
        key = jax.random.PRNGKey(self.seed)
        ndim = domain.ndim
        if not keep_params:
            self.params = self.net.init(key,
                                        jnp.zeros((1, ndim), jnp.float32))
        self.apply_fn = self.net.apply

        # -- adaptive configuration (reference models.py:68-105) ----------
        if Adaptive_type not in (0, 1, 2, 3):
            raise ValueError("Adaptive method invalid! (expected 0, 1, 2 or 3)")
        self.Adaptive_type = Adaptive_type
        self.isAdaptive = Adaptive_type in (1, 2)
        self.use_ntk = Adaptive_type == 3
        self.weight_outside_sum = Adaptive_type in (2, 3)
        self.dict_adaptive = dict_adaptive
        if self.use_ntk and (dict_adaptive is not None
                             or init_weights is not None):
            raise ValueError(
                "NTK weighting (type 3) computes all term weights from the "
                "tangent kernel; dict_adaptive/init_weights must be None")

        if self.isAdaptive:
            if dict_adaptive is None or init_weights is None:
                raise ValueError(
                    "Adaptive weights selected but no inputs were specified!")
            if all(not any(v) for v in dict_adaptive.values()):
                raise ValueError("Adaptive method was selected but no loss "
                                 "was marked to be adaptive")
            # tolerate omitted keys (treated as all-non-adaptive), but reject
            # unknown keys (silently dropping a misspelled 'bcs' would turn
            # the user's adaptivity off) and wrong lengths with clear messages
            for name, dct in (("dict_adaptive", dict_adaptive),
                              ("init_weights", init_weights)):
                unknown = set(dct) - {"residual", "BCs"}
                if unknown:
                    raise ValueError(
                        f"{name} has unknown key(s) {sorted(unknown)}; "
                        "expected only 'residual' and 'BCs'")
            dict_adaptive = {
                "residual": list(dict_adaptive.get("residual", [])),
                "BCs": list(dict_adaptive.get("BCs", [False] * len(self.bcs))),
            }
            init_weights = {
                "residual": list(init_weights.get("residual", [])),
                "BCs": list(init_weights.get("BCs", [None] * len(self.bcs))),
            }
            if len(dict_adaptive["BCs"]) != len(self.bcs):
                raise ValueError(
                    f"dict_adaptive['BCs'] has {len(dict_adaptive['BCs'])} "
                    f"entries but {len(self.bcs)} boundary conditions were "
                    "passed")
            for i, bc in enumerate(self.bcs):
                if dict_adaptive["BCs"][i] and (bc.isPeriodic or bc.isNeumann):
                    kind = "periodic" if bc.isPeriodic else "Neumann"
                    raise ValueError(
                        f"Adaptive {kind} boundary conditions are not "
                        "supported (reference models.py:138-140,159-161)")
            self.lambdas = initialize_lambdas(init_weights, dict_adaptive)
        else:
            if dict_adaptive is not None or init_weights is not None:
                raise ValueError(
                    "Adaptive weights are turned off but weight vectors were "
                    "provided; set them to None to continue")
            self.lambdas = {"residual": [], "BCs": []}

        self.X_f = jnp.asarray(domain.X_f, jnp.float32)
        # host copy of the current collocation set; the resample hook keeps
        # it fresh.  Host-side consumers (NTK live subsample) read this —
        # the device array can span non-addressable devices on a
        # multi-process mesh, where np.asarray(self.X_f) is illegal.
        self._X_f_host = np.asarray(domain.X_f, np.float32)
        if self.use_ntk:
            # one scalar weight per loss term, starting balanced at 1;
            # refreshed from NTK traces between training chunks
            n_res = self._count_residuals()
            self.lambdas = {
                "residual": [jnp.ones((), jnp.float32)] * n_res,
                "BCs": [jnp.ones((), jnp.float32)] * len(self.bcs)}
        self._build()
        self._compiled = True

    def _try_fuse(self):
        """Build the fused Taylor-propagation residual when both the network
        (standard tanh MLP) and ``f_model`` (analyzable grad-combinator use)
        qualify; ``None`` -> generic per-point engine.  Records the analysis
        failure in ``_fuse_fail_reason`` so ``fused=True`` errors show the
        real cause (e.g. a typo inside the user's f_model)."""
        from ..ops.fused import analyze_f_model, make_fused_residual, \
            mlp_qualifies

        self._fuse_fail_reason = None
        self._fuse_requests = None
        self._fuse_shapes = None
        layers = mlp_qualifies(self.net, self.params)
        if layers is None:
            return None
        requests, reason = analyze_f_model(
            self.f_model, self.domain.vars, self.n_out, return_reason=True)
        if requests is None:
            self._fuse_fail_reason = reason
            return None
        self._fuse_requests = requests
        # static layer dims, stashed for _autotune_engine's pallas
        # candidates — one qualification walk serves every consumer
        self._fuse_shapes = [(W.shape[0], W.shape[1]) for W, _ in layers]

        table_producer = None
        if self.fused == "pallas":
            from ..ops import pallas_taylor
            table_producer = pallas_taylor.build_pallas_table_fn(
                requests, self._fuse_shapes, precision=self.net.precision,
                interpret=not pallas_taylor.available(),
                compute_dtype=self.fused_dtype)
        return make_fused_residual(self.f_model, self.domain.vars, self.n_out,
                                   requests, precision=self.net.precision,
                                   table_producer=table_producer,
                                   compute_dtype=self.fused_dtype)

    def _autotune_engine(self):
        """Time one jitted loss+grad step per candidate residual engine on
        the real collocation set; return the fastest engine's residual_fn
        (``None`` = generic).  Engine choice is config-dependent (network
        width, N_f, backend), so measuring beats guessing."""
        candidates = {"generic": None, "fused": self._fused_residual}
        if getattr(self, "_fuse_requests", None) is not None:
            # the VMEM-resident pallas table producer competes too, but only
            # on real TPU hardware (interpret mode is not a perf candidate);
            # tile size changes the VMEM-residency/pipelining trade-off, so
            # a few tiles compete as separate candidates
            from ..ops import pallas_taylor
            from ..ops.fused import make_fused_residual
            if pallas_taylor.available():
                shapes = self._fuse_shapes
                # keep tiles strictly smaller than the point set (t == N
                # would make both training and the cross-check single-block,
                # and larger is pure padding waste) but always keep at least
                # one candidate — the kernel pads N < tile correctly
                tiles = [t for t in (512, 1024, 2048)
                         if t < int(self.X_f.shape[0])] or [512]
                # one sample size for every candidate: spans >=2 grid blocks
                # even for the largest tile AND shares one generic-reference
                # cache entry across all of them
                n_chk = max(tiles) + 1
                for tile in tiles:
                    producer = pallas_taylor.build_pallas_table_fn(
                        self._fuse_requests, shapes, tile=tile,
                        precision=self.net.precision,
                        compute_dtype=self.fused_dtype)
                    pallas_res = make_fused_residual(
                        self.f_model, self.domain.vars, self.n_out,
                        self._fuse_requests, precision=self.net.precision,
                        table_producer=producer,
                        compute_dtype=self.fused_dtype)
                    # same guard the XLA fused engine gets, run PER TILE:
                    # never adopt a kernel that disagrees numerically.
                    # Tile-shape-dependent miscompiles are exactly the
                    # hardware-only bug class interpret mode cannot see;
                    # n_chk > tile makes the comparison span at least two
                    # grid blocks, so cross-block accumulation/indexing
                    # bugs are exercised, not just the first padded block
                    ok, reason = self._crosscheck_fused(
                        n_check=n_chk, residual_fn=pallas_res)
                    if ok:
                        candidates[f"pallas-{tile}"] = pallas_res
                    else:
                        log_event("autotune",
                                  f"pallas tile={tile} excluded "
                                  f"({type(reason).__name__}: {reason})",
                                  verbose=self.verbose)
        timings = {}
        failures = {}
        for name, res_fn in candidates.items():
            try:
                # the shared measurement protocol (also the basis of the
                # minimax adoption race in _try_minimax)
                timings[name] = self._time_loss_step(residual_fn=res_fn)
            except Exception as e:  # a candidate that cannot even compile
                # (e.g. Mosaic lowering failure) is excluded, not fatal
                failures[name] = e
        if not timings:
            raise AutotuneFailure(failures)
        best = min(timings, key=timings.get)
        shown = ", ".join(f"{k}={v * 1e3:.2f}ms" for k, v in timings.items())
        for k, e in failures.items():
            shown += f", {k}=FAILED({type(e).__name__})"
        log_event("autotune", f"residual engine: {best} ({shown})",
                  verbose=self.verbose, engine=best,
                  timings_ms={k: v * 1e3 for k, v in timings.items()})
        return candidates[best]

    def _assemble_losses(self):
        """(Re)build ``loss_fn`` / ``loss_fn_refine`` from the selected
        residual engines and the CURRENT ``_causal_kw`` — called by
        ``compile`` and again by :meth:`_set_causal_eps` when the staged
        ε ladder advances (new jit keys; the persistent compile cache
        makes repeats warm).  An adopted minimax engine replaces the
        residual term of the training loss (and, in its full-precision
        flavor, of the refinement loss) with the single fused unit."""
        mm = getattr(self, "_minimax_loss", None)
        mm_refine = getattr(self, "_minimax_loss_refine", None)
        self.loss_fn = build_loss_fn(
            self.apply_fn, self.domain.vars, self.n_out, self.f_model,
            self.bcs, weight_outside_sum=self.weight_outside_sum, g=self.g,
            data_X=self.data_X, data_s=self.data_s,
            residual_fn=self._fused_residual, residual_loss_fn=mm,
            remat=self.remat, **self._causal_kw)
        self.loss_fn_refine = self.loss_fn
        if (self._refine_residual is not self._fused_residual
                or mm_refine is not mm):
            self.loss_fn_refine = build_loss_fn(
                self.apply_fn, self.domain.vars, self.n_out, self.f_model,
                self.bcs, weight_outside_sum=self.weight_outside_sum,
                g=self.g, data_X=self.data_X, data_s=self.data_s,
                residual_fn=self._refine_residual,
                residual_loss_fn=mm_refine, remat=self.remat,
                **self._causal_kw)

    def _set_causal_eps(self, eps: float):
        """Advance the causal-weighting tolerance (the annealing ladder,
        Wang et al. 2203.07404 Alg. 1) and re-assemble the losses."""
        self.causal_eps = float(eps)
        self._causal_kw["causal_eps"] = float(eps)
        self._assemble_losses()

    def _count_residuals(self) -> int:
        """Number of residual components ``f_model`` returns (trace once on
        a single point; multi-equation systems return a tuple)."""
        from ..ops.derivatives import make_ufn
        u = make_ufn(self.apply_fn, self.params, self.domain.vars, self.n_out)
        out = jax.eval_shape(
            lambda pt: self.f_model(u, *(pt[i] for i in range(self.domain.ndim))),
            jax.ShapeDtypeStruct((self.domain.ndim,), jnp.float32))
        return len(out) if isinstance(out, tuple) else 1

    def _crosscheck_fused(self, n_check: int = 32, residual_fn=None):
        """Numerically compare a fused residual engine against the generic
        autodiff engine on a small sample of the real collocation set.

        Static analysis (:func:`..ops.fused.analyze_f_model`) can only see
        how ``u`` is *used*; an f_model that is legal per-point but not
        pointwise when re-run batched (e.g. ``jnp.mean(u_x(x, t))``,
        ``jnp.stack([x, t])``-based terms, Python control flow on values)
        would silently compute a different loss.  One cheap forward of both
        engines catches every such case — and, for the pallas producer, a
        wrong-on-hardware kernel.  Returns ``(ok, reason)``."""
        from ..ops.fused import crosscheck_grads, crosscheck_residuals

        if residual_fn is None:
            residual_fn = self._fused_residual
        n_s = min(n_check, int(self.X_f.shape[0]))
        X_s = self.X_f[:n_s]

        def sumsq(out):
            comps = out if isinstance(out, tuple) else (out,)
            return sum(jnp.sum(jnp.asarray(c) ** 2) for c in comps)

        # the generic reference (values + gradient) depends only on
        # (params, n_s), both fixed within one compile — computed once,
        # shared across every autotune candidate
        cache = getattr(self, "_crosscheck_cache", None)
        if cache is None:
            cache = self._crosscheck_cache = {}
        if n_s not in cache:
            u = make_ufn(self.apply_fn, self.params, self.domain.vars,
                         self.n_out)
            generic = vmap_residual(self.f_model, u, self.domain.ndim)(X_s)

            def gen_loss(p):
                u_p = make_ufn(self.apply_fn, p, self.domain.vars,
                               self.n_out)
                return sumsq(vmap_residual(self.f_model, u_p,
                                           self.domain.ndim)(X_s))

            cache[n_s] = (generic, jax.grad(gen_loss)(self.params))
        generic, g_gen = cache[n_s]

        try:
            fused = residual_fn(self.params, X_s)
        except Exception as e:  # e.g. tracer bool error from control flow
            return False, e
        # reduced-precision matmuls legitimately drift further than the
        # float32 contraction-order band; bf16 has ~3 significant decimal
        # digits, compounded across layers — and the backward pass
        # compounds them twice (forward recompute + transposed chain), so
        # its band is wider still.  Structural bugs produce O(1) relative
        # errors, far outside either band.
        tols = {} if self.fused_dtype is None \
            else {"rtol": 5e-2, "atol": 1e-3}
        grad_tols = {} if self.fused_dtype is None \
            else {"rtol": 1.5e-1, "atol": 1e-3}
        ok, reason = crosscheck_residuals(generic, fused, **tols)
        if not ok:
            return ok, reason

        # The backward pass gets its own comparison: this round's
        # hardware-only kernel bugs (PERF.md) were in the backward kernel,
        # which a forward check never exercises.
        try:
            g_fus = jax.grad(lambda p: sumsq(residual_fn(p, X_s)))(self.params)
        except Exception as e:  # backward-only compile failure
            return False, e
        return crosscheck_grads(g_gen, g_fus, **grad_tols)

    def _try_minimax(self):
        """Build and cross-check the fused minimax loss engine
        (:mod:`..ops.pallas_minimax`); adopt it for the training loss when
        it qualifies and agrees with the generic loss numerically.  Records
        the disqualifying reason in ``_minimax_fail_reason`` (surfaced by
        ``minimax=True``)."""
        from ..ops import pallas_minimax as pmm

        try:
            if self._causal_kw:
                raise ValueError(
                    "causal weighting bins residuals across points; the "
                    "per-point minimax fusion cannot serve it")
            if self.remat:
                raise ValueError(
                    "remat wraps the residual evaluation; the fused "
                    "minimax loss already owns its memory layout")
            reqs = self._fuse_requests
            # E single-column equations (1 = the scalar family; a tuple-
            # returning f_model is an E-equation system, each component
            # getting its own λ/weight channel).  Raises for layouts the
            # per-point fusion cannot serve (multi-column components).
            n_eq = pmm.residual_columns(self.f_model, self.domain.vars,
                                        self.n_out, reqs)
            # pallas flavor only on real TPU hardware: interpret mode is a
            # test vehicle, not a training engine (the XLA fallback is the
            # CPU fast path — and what the interpret kernel is pinned
            # against in tests/test_pallas.py)
            use_pallas = pmm.available()
            sq = pmm.build_minimax_sq_fn(
                self.f_model, self.domain.vars, self.n_out, reqs,
                self._fuse_shapes, precision=self.net.precision,
                compute_dtype=self.fused_dtype, use_pallas=use_pallas,
                # the flat (GEMM-friendly) wavefront layout would reshape
                # across a GSPMD-sharded point axis under dist training
                flat_matmul=not self.dist)
            mm = pmm.make_minimax_residual_loss(
                sq, weight_outside_sum=self.weight_outside_sum, g=self.g)
            ok, why = self._crosscheck_minimax(mm)
            if not ok:
                raise ValueError(
                    "minimax engine failed the numeric cross-check "
                    "against the generic loss") from why
            if self.fused == "autotune" and self.minimax is not True:
                # autotune's contract is MEASURED engine choice: the
                # minimax unit replaces the timed winner's residual term,
                # so it must beat the unfused step it displaces, not just
                # agree numerically (engine speed is config-dependent —
                # the premise of autotune)
                t_mm = self._time_loss_step(residual_loss_fn=mm)
                t_un = self._time_loss_step(
                    residual_fn=self._fused_residual)
                if t_mm >= t_un:
                    raise ValueError(
                        f"autotune: minimax step measured slower than "
                        f"the selected residual engine "
                        f"({t_mm * 1e3:.2f}ms vs {t_un * 1e3:.2f}ms)")
                log_event("autotune",
                          f"minimax loss step: {t_mm * 1e3:.2f}ms vs "
                          f"unfused {t_un * 1e3:.2f}ms — adopting",
                          verbose=self.verbose,
                          timings_ms={"minimax": t_mm * 1e3,
                                      "unfused": t_un * 1e3})
            self._minimax_loss = mm
            self._minimax_kind = "pallas" if use_pallas else "xla"
            self._minimax_sq = sq        # the ascent resampler's free-∂X hook
            self._minimax_n_eq = n_eq    # E: widened cost basis + w sizing
            self._minimax_loss_refine = mm
            if self.fused_dtype is not None:
                # full-precision flavor for L-BFGS retreat (same engine,
                # full-precision matmuls)
                sq32 = pmm.build_minimax_sq_fn(
                    self.f_model, self.domain.vars, self.n_out, reqs,
                    self._fuse_shapes, precision=self.net.precision,
                    use_pallas=use_pallas, flat_matmul=not self.dist)
                self._minimax_loss_refine = pmm.make_minimax_residual_loss(
                    sq32, weight_outside_sum=self.weight_outside_sum,
                    g=self.g)
            log_event("fuse", "minimax engine adopted "
                      f"({self._minimax_kind}: residual + SA-λ loss + "
                      "cotangents + λ-ascent in one fusion)",
                      verbose=self.verbose, engine=self._minimax_kind)
        except Exception as e:
            self._minimax_fail_reason = e
            if self.minimax is True:
                raise ValueError(
                    "minimax=True but the fused minimax engine cannot be "
                    "adopted") from e
            log_event("fuse", f"minimax engine not adopted "
                      f"({type(e).__name__}: {e}); keeping the unfused "
                      "loss", verbose=self.verbose)

    def _minimax_score_grad_fn(self):
        """``score_grad(params, X) -> (scores [N], gX [N, d])`` for the
        PACMANN ascent resampler, built from the adopted fused minimax
        unit: ONE ``jax.vjp`` of ``sq(layers, 1, X)`` yields the
        per-point scores (the ``∂/∂w`` cotangent IS ``f_{e,p}²`` —
        summed over equations) AND ``∂/∂X``, the ascent direction — no
        differentiation beyond what the training step already fuses.
        ``None`` when the fused engine is not adopted (the resampler
        then falls back to ``value_and_grad`` over the compiled
        residual)."""
        sq = getattr(self, "_minimax_sq", None)
        if sq is None:
            return None
        n_eq = int(getattr(sq, "n_equations", 1))
        from ..ops.taylor import extract_mlp_layers

        def score_grad(params, X):
            layers = extract_mlp_layers(params)
            w = jnp.ones((X.shape[0], n_eq), X.dtype)
            val, vjp = jax.vjp(sq, layers, w, X)
            _, gw, gx = vjp(jnp.ones((), val.dtype))
            return jnp.sum(jnp.reshape(gw, (X.shape[0], -1)), axis=1), gx

        return score_grad

    def _time_loss_step(self, residual_fn=None, residual_loss_fn=None,
                        reps: int = 3):
        """Seconds per jitted loss+grad step over the full training loss
        with the given residual flavor — the same measurement
        :meth:`_autotune_engine` takes per candidate (warm-up compile
        excluded)."""
        import time as _time

        loss_fn = build_loss_fn(
            self.apply_fn, self.domain.vars, self.n_out, self.f_model,
            self.bcs, weight_outside_sum=self.weight_outside_sum,
            g=self.g, data_X=self.data_X, data_s=self.data_s,
            residual_fn=residual_fn, residual_loss_fn=residual_loss_fn,
            remat=self.remat, **self._causal_kw)

        def value_grad(params, X):
            return jax.value_and_grad(
                lambda p: loss_fn(p, self.lambdas["BCs"],
                                  self.lambdas["residual"], X)[0])(params)

        step = jax.jit(value_grad)
        out = step(self.params, self.X_f)  # compile + warm-up
        jax.block_until_ready(out)
        t0 = _time.perf_counter()
        for _ in range(reps):
            out = step(self.params, self.X_f)
        jax.block_until_ready(out)
        return (_time.perf_counter() - t0) / reps

    def _crosscheck_minimax(self, mm_loss, n_check: int = 32):
        """Numerically compare the fused minimax loss term (value AND
        gradients w.r.t. params and λ) against the generic engine's
        residual term on a sample of the real collocation set — the same
        gate :meth:`_crosscheck_fused` applies to residual values, now
        applied to the fully-fused loss unit whose forward already carries
        every cotangent.  Returns ``(ok, reason)``."""
        from ..ops.fused import FusedMismatch, crosscheck_grads

        n_s = min(n_check, int(self.X_f.shape[0]))
        X_s = self.X_f[:n_s]
        n_f = int(self.X_f.shape[0])
        lam_res = [lam[:n_s] if (lam is not None
                                 and getattr(lam, "ndim", 0) >= 1
                                 and lam.shape[0] == n_f) else lam
                   for lam in self.lambdas.get("residual", [])]
        # residual-term-only losses (no BC dilution): assembly's own λ
        # semantics on both sides, so the comparison can't drift from the
        # training loss
        gen = build_loss_fn(self.apply_fn, self.domain.vars, self.n_out,
                            self.f_model, [],
                            weight_outside_sum=self.weight_outside_sum,
                            g=self.g)
        mm = build_loss_fn(self.apply_fn, self.domain.vars, self.n_out,
                           self.f_model, [],
                           weight_outside_sum=self.weight_outside_sum,
                           g=self.g, residual_loss_fn=mm_loss)

        def val_grad(loss_fn):
            def f(p, lr_):
                return loss_fn(p, [], lr_, X_s)[0]
            return jax.value_and_grad(f, argnums=(0, 1))(self.params,
                                                         lam_res)

        try:
            v_m, g_m = val_grad(mm)
        except Exception as e:  # e.g. a Mosaic/vjp lowering failure
            return False, e
        v_g, g_g = val_grad(gen)
        rtol = 5e-3 if self.fused_dtype is None else 5e-2
        err = abs(float(v_m) - float(v_g))
        if not (err <= 1e-5 + rtol * abs(float(v_g))):  # NaN-safe form
            return False, FusedMismatch(
                f"minimax loss value {float(v_m):.6e} disagrees with the "
                f"generic engine's {float(v_g):.6e}")
        grad_tols = {} if self.fused_dtype is None \
            else {"rtol": 1.5e-1, "atol": 1e-3}
        return crosscheck_grads(g_g, g_m, **grad_tols)

    def _build(self):
        self._crosscheck_cache = {}  # generic reference, per (re)compile
        self._fused_residual = self._try_fuse() if self.fused is not False \
            else None
        if self.fused in (True, "pallas") and self._fused_residual is None:
            msg = ("fused=%r but the residual cannot be fused: it requires "
                   "the standard float32 tanh MLP and an f_model using "
                   "grad() combinators on untransformed coordinates with "
                   "derivative orders <= 3 (or unmixed 4th)" % (self.fused,))
            reason = getattr(self, "_fuse_fail_reason", None)
            if reason is not None:
                raise ValueError(f"{msg}; analysis stopped on: "
                                 f"{type(reason).__name__}: {reason}") \
                    from reason
            raise ValueError(msg)
        if self._fused_residual is not None:
            ok, reason = self._crosscheck_fused()
            if not ok:
                if self.fused in (True, "pallas"):
                    raise ValueError(
                        "fused residual failed the numeric cross-check "
                        "against the generic engine") from reason
                self._fuse_fail_reason = reason
                self._fused_residual = None
                log_event("fuse", f"cross-check failed "
                          f"({type(reason).__name__}: {reason}); using the "
                          "generic autodiff engine", verbose=self.verbose,
                          level="warning")
        if self.fused == "autotune":
            if self._fused_residual is not None:
                self._fused_residual = self._autotune_engine()
            else:
                reason = getattr(self, "_fuse_fail_reason", None)
                why = (f"{type(reason).__name__}: {reason}"
                       if reason is not None else "network is not the "
                       "standard float32 tanh MLP")
                log_event("autotune", f"fused engine excluded ({why}); "
                          "only the generic engine was considered",
                          verbose=self.verbose)
        if self.fused_dtype is not None and self._fused_residual is None:
            # the docstring promises "ignored with a warning" — honor it on
            # the silent-fallback path too (fused=None/'autotune' whose
            # engine failed to qualify), not just explicit fused=False
            import warnings
            warnings.warn(
                "fused_dtype was requested but no fused engine is active "
                "(the residual fell back to the generic autodiff engine); "
                "training runs full precision")
        # L-BFGS refinement engine: line searches break down on bf16
        # gradient noise (a second-order method amplifies ~5% derivative
        # error into failed Wolfe conditions), so under fused_dtype the
        # Newton phase gets a full-precision engine — bf16 Adam epochs,
        # f32 refinement.  Stored so the staged causal-ε ladder can
        # re-assemble both losses when ε advances.
        self._refine_residual = self._fused_residual
        if self.fused_dtype is not None and self._fused_residual is not None:
            from ..ops.fused import make_fused_residual as _mfr
            self._refine_residual = _mfr(
                self.f_model, self.domain.vars, self.n_out,
                self._fuse_requests, precision=self.net.precision)

        # fused minimax-step engine: residual + SA-λ loss + cotangents +
        # λ-ascent direction as one fusion replacing the training loss's
        # residual term (ops/pallas_minimax) — gated by the same numeric
        # cross-check discipline as the fused residual above
        self._minimax_loss = None
        self._minimax_loss_refine = None
        self._minimax_kind = None
        self._minimax_sq = None
        self._minimax_n_eq = 1
        self._minimax_fail_reason = None
        if self.minimax is not False and self._fused_residual is not None \
                and getattr(self, "_fuse_requests", None) is not None:
            self._try_minimax()
        elif self.minimax is True:
            reason = getattr(self, "_fuse_fail_reason", None)
            msg = ("minimax=True requires a fused residual engine "
                   "(standard float32 tanh MLP + analyzable f_model)")
            if reason is not None:
                raise ValueError(f"{msg}; analysis stopped on: "
                                 f"{type(reason).__name__}: {reason}") \
                    from reason
            raise ValueError(msg)
        self._assemble_losses()

        # jit-cached inference paths (params are traced args, so repeated
        # predict() calls reuse one compiled program)
        if self._fused_residual is not None:
            residual = self._fused_residual
        else:
            def residual(params, X):
                u = make_ufn(self.apply_fn, params, self.domain.vars,
                             self.n_out)
                return vmap_residual(self.f_model, u, self.domain.ndim)(X)

        self._residual_jit = jax.jit(residual)
        self._apply_jit = jax.jit(self.apply_fn)

        self._ntk_fn = None
        if getattr(self, "use_ntk", False):
            from ..ops.ntk import build_error_fns, make_ntk_weight_fn
            n_res = len(self.lambdas["residual"])
            bc_fns, res_all_fn, data_fn = build_error_fns(
                self.apply_fn, self.domain.vars, self.n_out, self.f_model,
                self.bcs, self.X_f, n_residuals=n_res,
                max_points=self.ntk_max_points,
                data_X=self.data_X, data_s=self.data_s)
            self._ntk_fn = make_ntk_weight_fn(bc_fns, res_all_fn, n_res,
                                              data_fn=data_fn,
                                              max_ratio=self.ntk_max_ratio)
            if data_fn is not None and "data" not in self.lambdas:
                self.lambdas["data"] = [jnp.ones((), jnp.float32)]

        # the cross-check cache holds param-sized gradient pytrees; it is
        # only useful within this build pass — release the device memory
        self._crosscheck_cache = {}

    # ------------------------------------------------------------------ #
    def compile_data(self, x, t, y):
        """Register observation data for assimilation
        (reference ``models.py:107-114`` — which stores but never *uses* the
        data, SURVEY §3.6; here it becomes a real ``Data`` loss term)."""
        if not self.assimilate:
            raise ValueError(
                "Assimilate needs to be set to 'true' for data assimilation. "
                "Re-initialize CollocationSolverND with assimilate=True.")
        # normalise spatial coords: accept an [n, d-1] array or a list of
        # per-variable columns (hstack column-wise; a plain reshape would
        # interleave coordinates for multi-dimensional spatial input)
        if isinstance(x, (list, tuple)):
            x = np.hstack([np.reshape(c, (-1, 1)) for c in x])
        else:
            x = np.reshape(np.asarray(x), (np.shape(np.ravel(x))[0] //
                                           max(self.domain.ndim - 1, 1), -1))
        t = np.reshape(t, (-1, 1))
        if x.shape[0] != t.shape[0]:
            raise ValueError(
                f"compile_data: {x.shape[0]} spatial rows vs {t.shape[0]} "
                "time rows")
        self.data_X = jnp.asarray(np.hstack([x, t]), jnp.float32)
        self.data_s = jnp.asarray(np.reshape(y, (-1, self.n_out)), jnp.float32)
        if self._compiled:
            self._build()

    # ------------------------------------------------------------------ #
    def _sync_X_f_host(self) -> np.ndarray:
        """Host copy of the LIVE collocation set.  Device-resident
        resampling leaves the mirror stale (``None``) instead of paying a
        device→host pull per redraw; host-side consumers (NTK residual
        subsample, restore templates) re-sync lazily here.  On a
        multi-process mesh the global array is assembled from each
        process's addressable shards (``np.asarray`` on a cross-host
        array is illegal)."""
        host = getattr(self, "_X_f_host", None)
        if host is not None:
            return host
        X = self.X_f
        if getattr(X, "is_fully_addressable", True):
            host = np.asarray(X, np.float32)
        else:
            from ..ops.resampling import gather_rows_multihost
            host = np.asarray(gather_rows_multihost(X), np.float32)
        self._X_f_host = host
        return host

    # ------------------------------------------------------------------ #
    def update_loss(self):
        """Current composite loss and components on the full collocation set
        (debug/inspection parity with reference ``models.py:116-218``)."""
        total, comps = self.loss_fn(
            self.params, self.lambdas["BCs"], self.lambdas["residual"],
            self.X_f, lam_data=self.lambdas.get("data", (None,))[0])
        return total, comps

    # ------------------------------------------------------------------ #
    def fit(self, tf_iter: int = 0, newton_iter: int = 0,
            batch_sz: Optional[int] = None,
            newton_eager: Optional[bool] = None,
            chunk: int = 100, profile_dir: Optional[str] = None,
            eval_fn: Optional[Callable] = None, eval_every: int = 0,
            resample_every: int = 0, resample_pool: int = 4,
            resample_temp: float = 1.0, resample_uniform: float = 0.1,
            resample_seed: int = 0, resample_device: Optional[bool] = None,
            resample_mode: str = "pool",
            resample_ascent_steps: int = 5,
            checkpoint_dir: Optional[str] = None,
            checkpoint_every: int = 0,
            telemetry=None, grad_clip: Optional[float] = None):
        """Adam phase then L-BFGS refinement (reference ``models.py:227`` →
        ``fit.py:17-102``).

        ``newton_eager`` selects the reference's two L-BFGS flavors
        (``fit.py:60-89``): ``True`` = the eager loop's *fixed-step* update
        (lr=0.8, ``optimizers.py:114``), ``False`` = the tfp graph path's
        strong-Wolfe line search.  Here both run as the same on-device jitted
        ``lax.scan``; the flag only switches the step rule.  Default ``None``
        uses the line search (more robust; the fixed-step variant exists for
        dynamics parity with reference results).

        ``profile_dir``: capture an XLA profiler trace of the whole run into
        this directory (first-class version of the reference's commented-out
        ``tf.profiler`` stubs, ``fit.py:39,57-59`` — SURVEY §5).

        ``eval_fn(phase, step, params)`` + ``eval_every``: periodic in-run
        evaluation hook (e.g. rel-L2 timelines for time-to-accuracy
        benchmarks) firing at chunk boundaries of both phases — training
        state, L-BFGS curvature memory, and compiled runners stay warm, so
        the measurement is of ONE continuous run.

        ``checkpoint_dir`` + ``checkpoint_every``: save the FULL training
        state (:meth:`save_checkpoint` — params, λ, Adam moments, loss
        history) every that many epochs, at chunk boundaries, WITHOUT
        interrupting the warm compiled run.  A killed process resumes by
        compiling the same config, :meth:`restore_checkpoint`, and calling
        ``fit`` with the remaining iteration budget (``len(solver.losses)``
        says how far it got).  Built for preemptible/intermittent
        accelerator time, where a 90-minute run must survive the backend
        dying at minute 80.  During L-BFGS the checkpoint carries the
        current params (the curvature pairs rebuild in a few iterations on
        resume).

        ``resample_every`` (beyond-reference; :mod:`..ops.resampling`):
        every that many Adam epochs, redraw the N_f collocation points by
        residual-importance sampling from a ``resample_pool``×N_f
        candidate pool (``p ∝ |f|^resample_temp`` with a
        ``resample_uniform`` floor).  Shapes and sharding are preserved,
        so the compiled step and Adam moments carry on; the L-BFGS phase
        refines on the final redraw.

        ``resample_device`` selects the implementation.  Default
        (``None``/``True``): the **device-resident** redraw — pool
        generation (stratified ``jax.random``), residual scoring, and
        Gumbel top-k selection run as ONE jitted program under the
        training sharding, double-buffered behind the training chunks
        (dispatched at the due boundary, swapped in at the next — the
        pool/score/select wall time hides behind compute; the selection
        is one chunk stale, the PACMANN-style pipelining trade).  Its
        pool is ``[current points ; fresh candidates]``, so selected
        current rows KEEP their per-point residual λ (gathered on-device
        alongside their points, λ-ascent Adam moments included) and fresh
        rows initialize from the adaptive SA-λ schedule (the carried
        distribution's current mean, arXiv:2207.04084) — per-point λ
        (Adaptive_type=1) therefore composes with resampling.
        ``False``: the original host path (numpy LHS pool, host Gumbel
        top-k, synchronous) — kept as the cross-implementation reference;
        it still raises under Adaptive_type=1 (its pool is entirely
        fresh).  Each redraw lands in telemetry (``resample.*`` gauges:
        kept fraction, score gain, λ drift, host-visible stall) and as a
        ``train.resample`` span.

        ``resample_mode="ascent"`` (device path only) selects the PACMANN
        mover (arXiv:2411.19632) instead of pool→top-k: the current
        points take ``resample_ascent_steps`` normalized-gradient steps
        UP the residual-magnitude landscape (clipped to the domain box),
        with a stratified fresh draw of ``resample_uniform``×N_f points
        replacing the lowest-score rows as the coverage floor
        (``resample_pool``/``resample_temp`` are pool-path knobs and are
        ignored).  When the fused minimax engine is adopted, the per-point
        scores and the ascent direction both come from ONE ``jax.vjp`` of
        the fused ``sq`` unit — ``∂/∂w`` IS ``f²`` per point/equation and
        ``∂/∂X`` is the move direction — so scoring costs no extra
        differentiation.  Moved points keep their row, so per-point λ and
        its ascent moments ride through unchanged; the redraw is the same
        pipelined, host-hop-free single program as the pool path.

        ``telemetry`` (beyond-reference;
        :mod:`tensordiffeq_tpu.telemetry`): a
        :class:`~tensordiffeq_tpu.telemetry.TrainingTelemetry` subscriber
        or a bare :class:`~tensordiffeq_tpu.telemetry.RunLogger` (wrapped
        with defaults).  The run then emits structured events — config,
        per-epoch loss components + gradient global-norm, SA-λ
        distribution summaries, step-time breakdown, checkpoint writes —
        and the NaN/Inf sentinel raises a structured
        :class:`~tensordiffeq_tpu.telemetry.TrainingDiverged` instead of
        letting a poisoned history run to the end.  Render the resulting
        run directory with :func:`tensordiffeq_tpu.telemetry.report`.

        ``grad_clip`` (beyond-reference;
        :mod:`tensordiffeq_tpu.resilience`): global-norm gradient clipping
        inside the Adam optimizer — the divergence-recovery remedy rung
        :class:`~tensordiffeq_tpu.resilience.ResilientFit` threads through
        here.  Toggling it changes the optimizer-state pytree, so a resume
        across the toggle restarts the Adam moments (checkpoint meta
        records the active value so restores build a matching template).

        Preemption (:mod:`tensordiffeq_tpu.resilience.preemption`): a
        pending SIGTERM/SIGINT request — or an injected chaos preemption —
        is noticed at the next chunk boundary of either phase; the final
        state is flushed through the ``checkpoint_dir`` hook and
        :class:`~tensordiffeq_tpu.resilience.Preempted` is raised."""
        if not self._compiled:
            raise NotCompiledError("Call compile(...) before fit(...)")
        if profile_dir is not None:
            from ..profiling import trace
            with trace(profile_dir):
                return self.fit(tf_iter=tf_iter, newton_iter=newton_iter,
                                batch_sz=batch_sz, newton_eager=newton_eager,
                                chunk=chunk, eval_fn=eval_fn,
                                eval_every=eval_every,
                                checkpoint_dir=checkpoint_dir,
                                checkpoint_every=checkpoint_every,
                                resample_every=resample_every,
                                resample_pool=resample_pool,
                                resample_temp=resample_temp,
                                resample_uniform=resample_uniform,
                                resample_seed=resample_seed,
                                resample_device=resample_device,
                                resample_mode=resample_mode,
                                resample_ascent_steps=resample_ascent_steps,
                                telemetry=telemetry, grad_clip=grad_clip)
        tele = as_training_telemetry(telemetry)
        epochs_at_entry = len(self.losses)
        if tele is not None:
            # the analytic FLOP floor guards the live cost model: a
            # compiled-step count below it means XLA's cost analysis was
            # blinded by a custom call (pallas scores zero) and must not
            # be quoted as-is (telemetry.costmodel).  Priced on the
            # PER-STEP batch, not N_f: a minibatched step legitimately
            # executes batch_sz points' worth of FLOPs, and an N_f floor
            # would discard its honest compiled count and inflate MFU.
            from ..telemetry.costmodel import analytic_step_floor
            n_f_total = int(self.X_f.shape[0])
            step_points = (n_f_total if batch_sz is None
                           else min(int(batch_sz), n_f_total))
            tele.cost_floor = analytic_step_floor(step_points,
                                                  self.layer_sizes)
            mm_kind = getattr(self, "_minimax_kind", None)
            if mm_kind is not None:
                # the minimax kernel is a custom call XLA's cost model
                # scores at zero FLOPs — substitute the channel-exact
                # analytic count of the fused step when the floor guard
                # trips, and disclose the basis (telemetry.costmodel)
                from ..ops.pallas_minimax import n_channels
                from ..telemetry.costmodel import analytic_minimax_flops
                tele.cost_fallback = (
                    analytic_minimax_flops(
                        self.layer_sizes, step_points,
                        n_channels(self._fuse_requests),
                        n_equations=getattr(self, "_minimax_n_eq", 1)),
                    "analytic-minimax")
            tele.on_fit_start(dict(
                tf_iter=tf_iter, newton_iter=newton_iter, batch_sz=batch_sz,
                N_f=int(self.X_f.shape[0]),
                layer_sizes=list(self.layer_sizes),
                Adaptive_type=self.Adaptive_type, dist=self.dist,
                engine=(f"fused-minimax-{mm_kind}" if mm_kind == "pallas"
                        else "fused-minimax" if mm_kind is not None
                        else "fused" if self._fused_residual is not None
                        else "generic"),
                resample_every=resample_every,
                causal_ladder=list(getattr(self, "causal_ladder", []) or []),
                prior_epochs=epochs_at_entry,
                prior_newton=int(getattr(self, "newton_done", 0))))
        if self.verbose:
            print_screen(self)

        mesh = None
        if self.dist:
            from ..parallel import resolve_mesh, shard_data_inputs
            mesh = resolve_mesh(self.dist)
            # persist the (possibly trimmed) sharded arrays so X_f and
            # per-point λ stay row-consistent across fit()/update_loss() calls
            self.X_f, self.lambdas = shard_data_inputs(self.X_f, self.lambdas,
                                                       mesh=mesh)
            host = getattr(self, "_X_f_host", None)
            if host is not None and host.shape[0] != int(self.X_f.shape[0]):
                # shard_data_inputs trims to a device multiple (prefix slice)
                self._X_f_host = host[: int(self.X_f.shape[0])]
        X_f = self.X_f
        lambdas = self.lambdas

        resample_fn = None
        if resample_every > 0:
            n_f = int(X_f.shape[0])
            per_point = any(
                lam is not None and getattr(lam, "ndim", 0) >= 1
                and lam.shape[0] == n_f
                for lam in lambdas.get("residual", []))
            # remedy-ladder floor (resilience.ResilientFit's
            # resample_uniform rung): a drift-induced divergence bumps
            # this so post-rollback redraws explore more uniformly
            # instead of re-concentrating onto the same hot set
            uniform_frac = max(
                float(resample_uniform),
                float(getattr(self, "_resample_uniform_floor", 0.0) or 0.0))
            # fit_adam restarts epoch numbering at 0 each call; offset by the
            # epochs already trained so a warm-restarted fit() explores new
            # pools instead of replaying the previous run's draws
            epoch_offset = len(self.losses)
            if resample_mode not in ("pool", "ascent"):
                raise ValueError(
                    f"resample_mode={resample_mode!r}: expected 'pool' "
                    "(pool→top-k redraw) or 'ascent' (PACMANN gradient "
                    "mover)")
            if resample_mode == "ascent":
                if resample_device is False:
                    raise ValueError(
                        "resample_mode='ascent' is device-resident by "
                        "construction (the mover is a jitted gradient "
                        "program); it has no host path — drop "
                        "resample_device=False")
                from ..ops.resampling import AscentResampler
                sampler = AscentResampler(
                    self._residual_jit, self.domain.xlimits, n_f,
                    n_steps=resample_ascent_steps,
                    fresh_frac=uniform_frac, seed=resample_seed,
                    like=X_f,
                    score_grad_fn=self._minimax_score_grad_fn())
                resample_fn = _DeviceResampleHook(self, sampler,
                                                  epoch_offset)
            elif resample_device is not False:
                # device-resident (default): pool→score→select in one
                # jitted program, double-buffered behind the training
                # chunks by fit_adam; kept rows carry per-point λ, so
                # Adaptive_type=1 composes
                from ..ops.resampling import DeviceResampler
                sampler = DeviceResampler(
                    self._residual_jit, self.domain.xlimits, n_f,
                    pool_factor=resample_pool, temp=resample_temp,
                    uniform_frac=uniform_frac, seed=resample_seed,
                    like=X_f)
                resample_fn = _DeviceResampleHook(self, sampler,
                                                  epoch_offset)
            else:
                if per_point:
                    raise ValueError(
                        "resample_device=False (the host-path redraw) is "
                        "incompatible with per-point residual λ "
                        "(Adaptive_type=1): the host pool is entirely "
                        "fresh, so trained λ rows have no points to ride. "
                        "Use the device-resident path (resample_device="
                        "None/True, the default), which keeps the current "
                        "points in the pool and carries kept rows' λ "
                        "through the redraw.")
                from ..ops.resampling import make_residual_resampler
                base_resampler = make_residual_resampler(
                    self._residual_jit, self.domain.xlimits, n_f,
                    pool_factor=resample_pool, temp=resample_temp,
                    uniform_frac=uniform_frac, seed=resample_seed,
                    like=X_f)

                def resample_fn(params, epoch):
                    X_new = base_resampler(params, epoch + epoch_offset)
                    # later phases (L-BFGS) and fit() calls use the final
                    # redraw
                    self.X_f = X_new
                    host = getattr(base_resampler, "last_host", None)
                    if host is not None:
                        self._X_f_host = host
                    return X_new

        # L-BFGS iterations completed BEFORE this fit call (nonzero only
        # after a checkpoint restore) — checkpoint metadata records
        # absolute refinement progress so a third window resumes correctly
        newton_prior = int(getattr(self, "newton_done", 0))
        ckpt_hook = None
        if checkpoint_dir is not None and checkpoint_every > 0:
            from ..checkpoint import save_checkpoint as _save_ck

            def ckpt_hook(trainables, opt_state, epoch, newton_done=0,
                          best=None, phase="adam"):
                # write directly from the LIVE buffers (solver attributes
                # only re-sync after the phase; the run's donated buffers
                # are valid exactly now, at this chunk boundary).  Each
                # save serialises the full loss history — the restore
                # contract needs it — so per-save meta cost grows linearly
                # with epochs trained: ~1 MB at 20k epochs, fine at the
                # intended every-1000-epochs cadence; don't set
                # checkpoint_every to single digits on month-long runs
                state = {"params": trainables["params"],
                         "lambdas": trainables["lambdas"]}
                if opt_state is not None:
                    state["opt_state"] = opt_state
                # sampler state: the CURRENT collocation set (adaptive
                # resampling mutates it) rides every checkpoint, so a
                # resume trains the points this run was actually training
                # — and under dist it rides per-shard, re-sharding onto
                # whatever topology the restore finds
                state["X_f"] = self.X_f
                min_loss = {k: float(v) for k, v in self.min_loss.items()}
                best_epoch = dict(self.best_epoch)
                # best-model snapshot: solver attributes only sync after a
                # phase returns, so collect every best iterate KNOWN at
                # this boundary — the current phase's LIVE running best
                # (threaded in by fit_adam / lbfgs_minimize) plus any
                # already-synced or restored phase best — and save the
                # winner's params, so a kill/resume keeps
                # predict(best_model=True) honest across legs
                cand = []
                if best is not None and np.isfinite(float(best[1])):
                    bl, bi = float(best[1]), int(best[2])
                    cand.append((bl, bi, phase, best[0]))
                    if bl < min_loss.get(phase, np.inf):
                        min_loss[phase] = bl
                        best_epoch[phase] = bi
                for ph in ("adam", "l-bfgs"):
                    if (ph == phase == "adam"
                            and getattr(self, "_ladder_active", False)):
                        # mid-ladder: a stored Adam best carries another ε
                        # stage's loss scale and does not compare with the
                        # live best — the live (current-stage) one wins
                        continue
                    bp = self.best_model.get(ph)
                    if bp is not None and np.isfinite(
                            float(self.min_loss.get(ph, np.inf))):
                        cand.append((float(self.min_loss[ph]),
                                     int(self.best_epoch[ph]), ph, bp))
                meta = {"losses": self.losses,
                        "min_loss": min_loss,
                        "best_epoch": best_epoch,
                        # L-BFGS iterations completed at save time, so a
                        # resume can credit the refinement phase too
                        # (the loss history counts only Adam epochs
                        # until the phase returns)
                        "newton_done": int(newton_done),
                        "has_opt_state": opt_state is not None,
                        "has_X_f": True,
                        # the saved collocation row count: a different
                        # topology's restore builds its template at THIS
                        # count, then re-trims for its own mesh
                        "n_f": int(np.shape(self.X_f)[0]),
                        # restores rebuild the opt_state template with the
                        # same clipping config, or the pytrees won't match
                        "grad_clip": grad_clip,
                        # sampler state beyond X_f: the remedy-ladder
                        # uniform floor, so a relaunched run keeps the
                        # calmer redraw distribution the supervisor chose
                        "resample_uniform_floor": float(getattr(
                            self, "_resample_uniform_floor", 0.0) or 0.0)}
                if cand:
                    bl, bi, ph, bp = min(cand, key=lambda c: c[0])
                    state["best_params"] = bp
                    meta.update(has_best=True, best_phase=ph,
                                best_loss=bl, best_iter=bi)
                _save_ck(checkpoint_dir, state, meta)
                if tele is not None:
                    # epoch arrives stage-rebased; add the restored history
                    # so the event is absolute (L-BFGS: newton_done already is)
                    tele.on_checkpoint(phase,
                                       int(newton_done)
                                       if phase == "l-bfgs"
                                       else epoch + epochs_at_entry)

        result = FitResult()
        result.losses = self.losses
        if tf_iter > 0:
            freeze = getattr(self, "use_ntk", False)
            if self.opt_state is not None and not opt_state_matches(
                    make_optimizer(self.lr, self.lr_weights,
                                   freeze_lambdas=freeze,
                                   grad_clip=grad_clip),
                    {"params": self.params, "lambdas": lambdas},
                    self.opt_state):
                # solver-managed state can go stale (e.g. λ rows trimmed by
                # dist sharding, or grad_clip toggled by a recovery rung);
                # restart the moments rather than erroring
                self.opt_state = None
            self._opt_grad_clip = grad_clip  # save_checkpoint records this
            ntk_update = self._ntk_fn
            if self._ntk_fn is not None and resample_fn is not None:
                # only when resampling: thread the LIVE collocation subsample
                # into the residual traces so the balance follows each
                # redraw.  The plain path keeps the compile-time points baked
                # inside jit.  residual_subsample reads the point set on the
                # host, which a cross-host device array forbids — so it reads
                # the maintained host copy (_X_f_host, refreshed by the
                # resample hook; identical on every process because the pool
                # draw and selection are seed-deterministic).
                from ..ops.ntk import residual_subsample

                def ntk_update(p):
                    return self._ntk_fn(
                        p, residual_subsample(
                            self._sync_X_f_host(),
                            getattr(self, "ntk_max_points", 256)))
            # staged causal-ε ladder (Wang et al. 2203.07404 Alg. 1): run
            # Adam at each ε in ascending order, advancing the moment the
            # causal gate opens (min Causal_w_last > causal_delta at a
            # chunk boundary); the remaining epoch budget carries over,
            # as do params / λ / Adam moments.  A single ε (or no causal
            # mode) degenerates to one plain fit_adam call.
            ladder = list(getattr(self, "causal_ladder", []) or [])
            stages = ladder if len(ladder) > 1 else [None]
            multi_stage = len(stages) > 1
            self._ladder_active = multi_stage  # read by ckpt_hook
            remaining = tf_iter
            stage_off = 0  # epochs consumed by earlier stages THIS fit call
            for si, eps in enumerate(stages):
                if eps is not None and eps != self.causal_eps:
                    if si == 0:
                        log_event("causal", f"ladder restart: ε -> {eps:g}",
                                  verbose=self.verbose, eps=eps)
                    else:
                        log_event("causal", f"gate open (w_last > "
                                  f"{self.causal_delta:g}); ε -> {eps:g} "
                                  f"({remaining} Adam epochs left)",
                                  verbose=self.verbose, eps=eps,
                                  remaining=remaining)
                    self._set_causal_eps(eps)
                stop_fn = None
                if si < len(stages) - 1:
                    def stop_fn(res, _d=self.causal_delta):
                        last = res.losses[-1] if res.losses else {}
                        w = [v for k, v in last.items()
                             if k.startswith("Causal_w_last")]
                        return bool(w) and min(w) > _d
                epochs_before = len(result.losses)
                wall_before = result.wall_time.get("adam", 0.0)
                # re-base stage-relative epochs to run-relative in every
                # host hook, so timelines / resume meta / pool draws stay
                # monotonic across stages (the L-BFGS leg's newton_prior
                # re-basing, one level up)
                off = stage_off

                def with_off(fn, _o=off):
                    return None if fn is None else (
                        lambda e, p: fn(e + _o, p))
                res_fn = resample_fn
                if resample_fn is not None and off:
                    if getattr(resample_fn, "pipelined", False):
                        # hook object: re-base via its stage offset (the
                        # dispatch/swap protocol has no wrappable call)
                        resample_fn.stage_offset = off
                    else:
                        def res_fn(p, e, _o=off):  # (params, epoch) order
                            return resample_fn(p, e + _o)
                hook = ckpt_hook
                if hook is not None and off:
                    def hook(tr, st, e, best=None, _o=off, **kw):
                        if best is not None:
                            best = (best[0], best[1], int(best[2]) + _o)
                        ckpt_hook(tr, st, e + _o, best=best, **kw)
                if tele is not None:
                    # telemetry epochs are run-relative: restored history
                    # plus the epochs earlier ε stages consumed this call
                    tele.epoch_offset = epochs_at_entry + off
                trainables, self.opt_state, result = fit_adam(
                    self.loss_fn, self.params, lambdas, X_f,
                    tf_iter=remaining, batch_sz=batch_sz, lr=self.lr,
                    lr_weights=self.lr_weights, chunk=chunk,
                    verbose=self.verbose, result=result,
                    opt_state=self.opt_state, freeze_lambdas=freeze,
                    lambda_update_fn=ntk_update, mesh=mesh,
                    callback=(None if eval_fn is None else
                              with_off(lambda e, p: eval_fn("adam", e, p))),
                    callback_every=eval_every,
                    resample_fn=res_fn,
                    resample_every=resample_every,
                    state_hook=hook, state_hook_every=checkpoint_every,
                    stop_fn=stop_fn, telemetry=tele, grad_clip=grad_clip,
                    epoch0=epochs_at_entry + off)
                self.params = trainables["params"]
                self.lambdas = lambdas = trainables["lambdas"]
                result.wall_time["adam"] += wall_before
                stage_epochs = len(result.losses) - epochs_before
                remaining -= stage_epochs
                stage_off += stage_epochs
                if remaining <= 0:
                    break
            if multi_stage and result.best_epoch["adam"] >= 0:
                # stage losses are weighted by different ε and do not
                # compare (the reset-on-redraw principle): the LAST —
                # strictest — stage's best is the run's best, recorded at
                # its run-relative epoch
                result.best_epoch["adam"] += stage_off - stage_epochs
            # adopt the leg's best only if it beats a best restored from a
            # checkpoint (a resumed leg must not clobber the pre-kill best
            # iterate) — except under resampling or a multi-stage causal
            # ladder, where losses from different point draws / ε stages
            # don't compare (same reset-on-redraw rule the in-run tracking
            # applies): there the current leg's final-stage best wins
            if (self.best_model["adam"] is None or resample_fn is not None
                    or multi_stage
                    or result.min_loss["adam"] <= self.min_loss["adam"]):
                self.best_model["adam"] = result.best_params["adam"]
                self.min_loss["adam"] = result.min_loss["adam"]
                self.best_epoch["adam"] = result.best_epoch["adam"]

        if newton_iter > 0:
            from ..training.lbfgs import fit_lbfgs

            # one composite callback serves both hooks at their own
            # cadences (fit_lbfgs exposes a single callback_every).  The
            # L-BFGS loop runs in chunks, so the callback sees chunk-
            # aligned iterate counts — each hook fires on CADENCE-BOUNDARY
            # CROSSINGS (same rule fit_lbfgs itself applies), never on
            # exact modulo, which a chunk boundary would usually miss.
            lb_every = min((v for v in (eval_every if eval_fn else 0,
                                        checkpoint_every if ckpt_hook else 0)
                            if v > 0), default=0)
            lb_prev = {"i": 0}

            def lb_callback(i, p, best=None):
                prev, lb_prev["i"] = lb_prev["i"], i
                # checkpoint BEFORE eval: the resume meta a caller writes
                # from its eval hook must never describe state newer than
                # the checkpoint on disk (see fit.py state_hook contract)
                if ckpt_hook is not None and checkpoint_every > 0 \
                        and prev // checkpoint_every != i // checkpoint_every:
                    # params advance; λ and Adam moments ride unchanged, so
                    # a resume re-enters L-BFGS from the latest iterate
                    ckpt_hook({"params": p, "lambdas": self.lambdas},
                              self.opt_state, i,
                              newton_done=newton_prior + i,
                              # the live best counts iterations within THIS
                              # leg; re-base to absolute so saved meta agrees
                              # with the absolute newton_done beside it
                              best=(None if best is None else
                                    (best[0], best[1],
                                     newton_prior + int(best[2]))),
                              phase="l-bfgs")
                if eval_fn is not None and eval_every > 0 \
                        and prev // eval_every != i // eval_every:
                    eval_fn("l-bfgs", i, p)

            preempt_flush = None
            if ckpt_hook is not None:
                def preempt_flush(i, p, best):
                    # unconditional final flush (the cadence-gated
                    # lb_callback may have skipped this boundary); same
                    # re-basing as the periodic checkpoint path
                    ckpt_hook({"params": p, "lambdas": self.lambdas},
                              self.opt_state, i,
                              newton_done=newton_prior + i,
                              best=(None if best is None else
                                    (best[0], best[1],
                                     newton_prior + int(best[2]))),
                              phase="l-bfgs")

            refine_loss, refine_fallback = self.loss_fn_refine, None
            if self.fused_dtype is not None \
                    and self.loss_fn is not self.loss_fn_refine:
                # bf16 end-to-end: refinement starts on the bf16 fused
                # loss (the same rate the Adam phase ran at) and retreats
                # to the full-precision engine only when the Wolfe line
                # search stagnates — the PERF.md-documented bf16 failure
                # mode, now a fallback instead of a standing tax
                refine_loss, refine_fallback = (self.loss_fn,
                                                self.loss_fn_refine)
            params, best_params, best_loss, best_iter, lbfgs_losses = fit_lbfgs(
                refine_loss, self.params, self.lambdas, self.X_f,
                maxiter=newton_iter, verbose=self.verbose,
                eager=bool(newton_eager),
                callback=(lb_callback if lb_every > 0 else None),
                callback_every=lb_every, telemetry=tele,
                iter0=newton_prior, preempt_flush=preempt_flush,
                loss_fn_fallback=refine_fallback)
            self.params = params
            self.losses.extend(lbfgs_losses)
            if tele is not None:
                # iteration numbers are absolute refinement progress; a
                # NaN stop logs a divergence event (no raise — the loop
                # already stopped itself and kept its best iterate)
                tele.epoch_offset = 0
                tele.on_lbfgs_history(
                    [d["Total Loss"] for d in lbfgs_losses],
                    start_iter=newton_prior)
            # same adopt-if-better rule as the Adam phase: a resumed
            # refinement leg keeps the restored best when that's better
            if (self.best_model["l-bfgs"] is None
                    or float(best_loss) <= float(self.min_loss["l-bfgs"])):
                self.best_model["l-bfgs"] = best_params
                self.min_loss["l-bfgs"] = float(best_loss)
                # best_iter counts within this leg; record absolute
                self.best_epoch["l-bfgs"] = newton_prior + int(best_iter)
            # credit ACTUAL progress, not the requested budget: fit_lbfgs
            # can stop early (NaN stop / tolerance break), and a resume
            # must not skip refinement iterations that never ran
            self.newton_done = newton_prior + len(lbfgs_losses)

        # overall best selection (reference fit.py:95-102).  A phase whose
        # snapshot is None (skipped this call — e.g. a checkpoint-resumed
        # fit that re-enters straight into L-BFGS) can carry a restored
        # min_loss but must never win: picking a None model would silently
        # degrade predict(best_model=True) to the final iterate.
        adam_ok = self.best_model["adam"] is not None
        lbfgs_ok = self.best_model["l-bfgs"] is not None
        if adam_ok and (not lbfgs_ok
                        or self.min_loss["adam"] <= self.min_loss["l-bfgs"]):
            which, offset = "adam", 0
        else:
            which, offset = "l-bfgs", tf_iter
        self.min_loss["overall"] = self.min_loss[which]
        self.best_epoch["overall"] = self.best_epoch[which] + offset
        self.best_model["overall"] = self.best_model[which]
        if tele is not None:
            tele.on_fit_end(dict(
                epochs_total=len(self.losses),
                newton_done=int(getattr(self, "newton_done", 0)),
                min_loss={k: float(v) for k, v in self.min_loss.items()},
                best_epoch={k: int(v) for k, v in self.best_epoch.items()},
                wall_adam=float(result.wall_time.get("adam", 0.0))))
        return self

    # ------------------------------------------------------------------ #
    def predict(self, X_star, best_model: bool = False):
        """Evaluate the solution and the PDE residual at query points
        (reference ``models.py:297-313``).  Returns ``(u, f_u)`` as NumPy;
        ``f_u`` is a tuple for multi-equation systems."""
        params = (self.best_model["overall"]
                  if best_model and self.best_model["overall"] is not None
                  else self.params)
        X_star = jnp.asarray(X_star, jnp.float32)
        if not self._compiled:
            if not getattr(self, "_loaded", False):
                raise NotCompiledError(
                    "Call compile(...) or load_model(...) before "
                    "predict(...)")
            # loaded-but-uncompiled: the solution net exists, the PDE
            # residual does not (no f_model yet) — reference load_model
            # semantics (a bare Keras model, models.py:318-319)
            return np.asarray(self._apply_jit(params, X_star)), None
        u_star = self._apply_jit(params, X_star)
        f_star = self._residual_jit(params, X_star)
        if isinstance(f_star, tuple):
            f_np = tuple(np.asarray(f) for f in f_star)
            f_np = f_np[0] if len(f_np) == 1 else f_np
        else:
            f_np = np.asarray(f_star)
        return np.asarray(u_star), f_np

    # ------------------------------------------------------------------ #
    def export_surrogate(self, best_model: bool = False):
        """Export the trained solution as a deployable
        :class:`~tensordiffeq_tpu.serving.Surrogate`: network + params +
        the ``u``/derivative/residual closures, with **no training state**
        (no optimizer moments, no λ, no collocation set).  The artifact
        ``save``s through the checkpoint backend and restores in a fresh
        process (``Surrogate.load(path, f_model=...)``); batched queries go
        through ``surrogate.engine()``.  ``best_model=True`` exports the
        best iterate, as in :meth:`predict`."""
        if not self._compiled and not getattr(self, "_loaded", False):
            raise NotCompiledError(
                "Call compile(...) or load_model(...) before "
                "export_surrogate()")
        from ..serving import Surrogate
        return Surrogate.from_solver(self, best_model=best_model)

    # ------------------------------------------------------------------ #
    def save_checkpoint(self, path: str, sharded: Optional[bool] = None):
        """Checkpoint the FULL training state — params, SA λ, Adam moments,
        collocation set, loss history — under directory ``path`` (what the
        reference cannot do: its save/load drops λ and optimizer state,
        SURVEY §5).  ``sharded`` forwards to
        :func:`tensordiffeq_tpu.checkpoint.save_checkpoint`: ``None``
        auto-selects the topology-portable per-shard layout whenever the
        job is multi-process (``True`` forces it — how single-process
        tests exercise the elastic 8→4 restore format)."""
        from ..checkpoint import save_checkpoint
        state = {"params": self.params, "lambdas": self.lambdas}
        if self.opt_state is not None:
            state["opt_state"] = self.opt_state
        state["X_f"] = self.X_f
        meta = {"losses": self.losses,
                "min_loss": {k: float(v) for k, v in self.min_loss.items()},
                "best_epoch": dict(self.best_epoch),
                "newton_done": int(getattr(self, "newton_done", 0)),
                "has_opt_state": self.opt_state is not None,
                "has_X_f": True,
                "n_f": int(np.shape(self.X_f)[0]),
                "grad_clip": getattr(self, "_opt_grad_clip", None),
                "resample_uniform_floor": float(getattr(
                    self, "_resample_uniform_floor", 0.0) or 0.0)}
        # carry the best iterate too, so predict(best_model=True) survives
        # a save/restore cycle (phase buckets tie-break before "overall",
        # which always mirrors one of them — restores re-bucket by phase)
        cand = [(float(self.min_loss.get(ph, np.inf)), ph)
                for ph in ("adam", "l-bfgs", "overall")
                if self.best_model.get(ph) is not None
                and np.isfinite(float(self.min_loss.get(ph, np.inf)))]
        if cand:
            bl, ph = min(cand)
            state["best_params"] = self.best_model[ph]
            meta.update(has_best=True, best_phase=ph, best_loss=bl,
                        best_iter=int(self.best_epoch.get(ph, -1)))
        save_checkpoint(path, state, meta, sharded=sharded)
        log_event("checkpoint", f"saved full training state -> {path}",
                  verbose=False, path=str(path),
                  epochs=len(self.losses),
                  newton_done=int(getattr(self, "newton_done", 0)))

    def restore_checkpoint(self, path: str):
        """Restore a :meth:`save_checkpoint` state into this (compiled)
        solver.  The solver must be compiled with the same configuration so
        the state template matches.

        ``dist`` solvers: the collocation set and per-point λ are placed
        on the CURRENT device mesh *before* building the template (a
        checkpoint saved mid-dist-training has the trimmed row count), and
        the restored state — X_f, λ — is re-placed with its ``"data"``
        sharding after loading.  The restore is where elastic re-sharding
        happens: a checkpoint written on one topology (8 devices, 2
        hosts) comes back as global host arrays via the per-shard
        manifest and is re-sharded onto whatever mesh THIS solver was
        compiled with (``dist=4``, one surviving host, …) — training
        resumes sharded, no host-resident λ, sampler/λ/optimizer state
        intact."""
        if not self._compiled:
            raise NotCompiledError(
                "Call compile(...) before restore_checkpoint")
        from ..checkpoint import restore_checkpoint
        # peek at meta to know whether optimizer moments were saved (via
        # resolve_checkpoint_dir so the killed-mid-swap .old fallback the
        # restore itself applies is honoured here too)
        import json as _json
        import os as _os
        from ..checkpoint import resolve_checkpoint_dir
        with open(_os.path.join(resolve_checkpoint_dir(path),
                                "tdq_meta.json")) as fh:
            _meta_peek = _json.load(fh)["meta"]
        saved_nf = _meta_peek.get("n_f")
        mesh = None
        tmpl_lambdas = self.lambdas
        tmpl_X = self.X_f
        if self.dist:
            from ..parallel import resolve_mesh, shard_data_inputs
            mesh = resolve_mesh(self.dist)
            if saved_nf is None:
                # legacy checkpoint (no recorded row count): the old
                # contract — this mesh's trim must coincide with the
                # saved one, so place/trim before building the template
                self.X_f, self.lambdas = shard_data_inputs(
                    self.X_f, self.lambdas, mesh=mesh)
                tmpl_lambdas, tmpl_X = self.lambdas, self.X_f
            else:
                # elastic contract: build the template at the SAVED row
                # count (host-resident, values irrelevant — only
                # structure/shapes feed the load); the placement AND this
                # mesh's own trim happen AFTER the load, which is what
                # lets an 8-device checkpoint restore onto 4 devices even
                # when the two topologies trim N_f differently
                n_cur = int(np.shape(self.X_f)[0])
                base = getattr(self, "_X_f_host", None)
                if base is None or base.shape[0] < int(saved_nf):
                    base = np.asarray(self.domain.X_f, np.float32)
                tmpl_X = base[: int(saved_nf)]

                def _retrim(lam):
                    if lam is not None and getattr(lam, "ndim", 0) >= 1 \
                            and int(lam.shape[0]) == n_cur:
                        return np.zeros((int(saved_nf),) + tuple(lam.shape[1:]),
                                        np.float32)
                    return lam
                tmpl_lambdas = {k: [_retrim(l) if k == "residual" else l
                                    for l in v]
                                for k, v in self.lambdas.items()}
        template = {"params": self.params, "lambdas": tmpl_lambdas}
        if _meta_peek.get("has_opt_state", False):
            opt = make_optimizer(self.lr, self.lr_weights,
                                 freeze_lambdas=getattr(self, "use_ntk", False),
                                 grad_clip=_meta_peek.get("grad_clip"))
            template["opt_state"] = opt.init(
                {"params": self.params, "lambdas": tmpl_lambdas})
        if _meta_peek.get("has_X_f", False):
            template["X_f"] = tmpl_X
        if _meta_peek.get("has_best", False):
            template["best_params"] = self.params
        state, meta = restore_checkpoint(path, template)
        self.params = state["params"]
        self.lambdas = state["lambdas"]
        self.opt_state = state.get("opt_state")
        if "X_f" in state:
            # the checkpointed collocation set (adaptive resampling makes
            # it trained state); host-resident here, re-sharded below
            host_X = np.asarray(state["X_f"], np.float32)
            self._X_f_host = host_X
            self.X_f = host_X if mesh is not None \
                else jnp.asarray(host_X, jnp.float32)
        # the restored moments carry this clipping config; a fit() with a
        # different grad_clip restarts them (see the stale-state check)
        self._opt_grad_clip = _meta_peek.get("grad_clip")
        # sampler state: a supervisor-bumped redraw uniform floor survives
        # the relaunch (prevention, not rollback — resilience.recovery)
        floor = float(_meta_peek.get("resample_uniform_floor", 0.0) or 0.0)
        if floor > 0.0:
            self._resample_uniform_floor = floor
        if mesh is not None:
            # restored λ come back host-resident; re-apply the data-parallel
            # placement so per-point λ resume sharded alongside their points
            from ..parallel import shard_data_inputs
            self.X_f, self.lambdas = shard_data_inputs(
                self.X_f, self.lambdas, mesh=mesh)
        self.losses = list(meta.get("losses", []))
        for k, v in meta.get("min_loss", {}).items():
            self.min_loss[k] = float(v)
        for k, v in meta.get("best_epoch", {}).items():
            self.best_epoch[k] = int(v)
        if "best_params" in state:
            # re-bucket the saved best iterate so a resumed fit's
            # adopt-if-better rule competes against it, and mirror it into
            # "overall" so predict(best_model=True) works immediately
            ph = meta.get("best_phase", "adam")
            if ph in ("adam", "l-bfgs"):
                self.best_model[ph] = state["best_params"]
            self.best_model["overall"] = state["best_params"]
            self.min_loss["overall"] = float(meta.get("best_loss", np.inf))
            self.best_epoch["overall"] = int(meta.get("best_iter", -1))
        # L-BFGS iterations already completed when this checkpoint was
        # taken (0 for Adam-phase checkpoints) — resume helpers subtract
        # it from the refinement budget
        self.newton_done = int(meta.get("newton_done", 0))
        log_event("restore", f"restored training state from {path} "
                  f"({len(self.losses)} epochs, {self.newton_done} L-BFGS "
                  "iters on record)", verbose=False, path=str(path),
                  epochs=len(self.losses), newton_done=self.newton_done)
        return self

    # ------------------------------------------------------------------ #
    _SAVE_MAGIC = b"TDQM"

    def _arch_meta(self) -> dict:
        # the one shared describe path (networks.net_metadata) — embedding-net
        # hyperparameters ride along so load_model can rebuild them
        from ..networks import net_metadata
        return net_metadata(self.net, self.layer_sizes, self.n_out)

    def save(self, path: str):
        """Serialise the network — *self-describing*, like the reference's
        Keras SavedModel (``models.py:315-316``): architecture metadata
        (layer sizes, activation) is persisted alongside the parameters, so
        :meth:`load_model` can reconstruct the net without a pre-compiled
        solver.  Full training-state checkpoints (λ, optimizer moments) live
        in :mod:`tensordiffeq_tpu.checkpoint`."""
        import struct
        header = __import__("json").dumps(self._arch_meta()).encode("utf-8")
        with open(path, "wb") as fh:
            fh.write(self._SAVE_MAGIC + struct.pack("<Q", len(header))
                     + header + flax.serialization.to_bytes(self.params))

    def load_model(self, path: str, compile_model: bool = False):
        """Restore a network saved by :meth:`save`
        (reference ``models.py:318-319``).

        On a compiled solver the architecture in the file is validated
        against the compiled one.  On an *uncompiled* solver the standard
        MLP is reconstructed from the persisted metadata — no need to
        re-state ``layer_sizes`` — and a later
        ``compile(layer_sizes=None, ...)`` reuses the loaded network and
        parameters (the transfer-learn flow,
        reference ``examples/transfer-learn.py:56-72``)."""
        import json as _json
        import struct
        with open(path, "rb") as fh:
            raw = fh.read()
        if raw[:4] == self._SAVE_MAGIC:
            hlen = struct.unpack("<Q", raw[4:12])[0]
            meta = _json.loads(raw[12:12 + hlen].decode("utf-8"))
            blob = raw[12 + hlen:]
        else:  # legacy bare-params file from earlier versions
            meta, blob = None, raw

        if self._compiled:
            if meta is not None:
                if list(meta["layer_sizes"]) != list(self.layer_sizes):
                    raise ValueError(
                        f"saved model has layer_sizes {meta['layer_sizes']} "
                        f"but this solver was compiled with "
                        f"{self.layer_sizes}")
                # embedding nets compute a fixed function of their config
                # (Fourier B matrix, harmonic spec): a silent mismatch would
                # load weights into a *different* function, so compare the
                # full architecture record, not just the Dense shapes
                mine = self._arch_meta()
                for k in ("network_type", "net_config"):
                    if meta.get(k, mine.get(k)) != mine.get(k):
                        raise ValueError(
                            f"saved model {k} {meta.get(k)!r} does not "
                            f"match the compiled network's {mine.get(k)!r}")
            self.params = flax.serialization.from_bytes(self.params, blob)
            return self

        if meta is None:
            raise ValueError(
                "this file has no architecture metadata (saved by an older "
                "version); compile(...) the solver with the matching "
                "layer_sizes first, then load_model")
        from ..networks import net_from_metadata
        try:
            self.net = net_from_metadata(meta)
        except ValueError as e:
            raise ValueError(
                f"{e}; here: compile(..., network=...) before load_model") \
                from None
        self.layer_sizes = list(meta["layer_sizes"])
        self.n_out = int(meta.get("n_out", self.layer_sizes[-1]))
        template = self.net.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, self.layer_sizes[0]), jnp.float32))
        self.params = flax.serialization.from_bytes(template, blob)
        self.apply_fn = self.net.apply
        self._apply_jit = jax.jit(self.apply_fn)
        self._loaded = True
        return self
