"""Composite PINN loss assembly.

Builds the pure loss function at the heart of the solver — the TPU-native
re-design of the reference's ``CollocationSolverND.update_loss``
(``models.py:116-218``).  Differences by design:

* **Pure & functional**: ``loss(params, lam_bcs, lam_res, X_batch) ->
  (total, components)`` with all BC meshes/targets closed over as jit-time
  constants.  No mutation, no ``self.losses`` side channel — component losses
  are returned, the trainer records them.
* **Structural λ routing**: λ vectors arrive as per-term lists (``None`` for
  non-adaptive terms), eliminating the reference's index-map arithmetic and
  its shared-index bug for multiple adaptive residuals (SURVEY §2.4.4).
* **Residuals via per-point autodiff**: the user ``f_model`` is evaluated
  through :func:`tensordiffeq_tpu.ops.derivatives.vmap_residual` — per-point
  ``jax.grad`` chains vmapped over the collocation batch, replacing batched
  ``tf.gradients`` (reference ``models.py:187``).
* **Periodic BCs match every derivative** returned by the user's
  ``deriv_model`` (the reference's nested index loop only matches the first,
  ``models.py:143-149``).
* **Data assimilation is a real loss term** (the reference stores the data
  but never uses it — SURVEY §3.6).

Self-adaptive weighting follows McClenny et al. (arXiv:2009.04544) exactly as
the reference implements it: type 1 weights point-wise inside the mean, type 2
scales each term's mean, optional ``g(λ)`` transform on residual terms
(``models.py:196-208``, ``utils.py:38-48``).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from ..boundaries import BC
from ..ops.derivatives import UFn, make_ufn, vmap_residual
from ..ops.losses import MSE, causal_residual_loss, g_MSE


def _as_tuple(x):
    return x if isinstance(x, tuple) else (x,)


def _vmap_deriv(deriv_fn: Callable, u: UFn, pts: jnp.ndarray):
    """Evaluate a user ``deriv_model(u, *coords)`` over an ``[n, d]`` face
    mesh; returns a tuple of ``[n]`` arrays (one per returned derivative)."""
    ndim = pts.shape[1]

    def per_point(pt):
        return _as_tuple(deriv_fn(u, *(pt[i] for i in range(ndim))))

    return jax.vmap(per_point)(pts)


def build_loss_fn(apply_fn: Callable,
                  varnames: Sequence[str],
                  n_out: int,
                  f_model: Callable,
                  bcs: Sequence[BC],
                  weight_outside_sum: bool = False,
                  g: Optional[Callable] = None,
                  data_X: Optional[jnp.ndarray] = None,
                  data_s: Optional[jnp.ndarray] = None,
                  residual_fn: Optional[Callable] = None,
                  residual_loss_fn: Optional[Callable] = None,
                  causal_eps: Optional[float] = None,
                  causal_bins: int = 32,
                  time_index: Optional[int] = None,
                  time_bounds: Optional[tuple] = None,
                  remat: bool = False) -> Callable:
    """Assemble ``loss(params, lam_bcs, lam_res, X_batch)``.

    Args:
      apply_fn: batched network apply ``(params, x[..., d]) -> y[..., n_out]``.
      varnames: domain variable names, in column order of ``X_batch``.
      n_out: network output dimension.
      f_model: user residual ``f_model(u, *coords)`` (per-point, JAX-style).
      bcs: boundary/initial condition objects (host data already built).
      weight_outside_sum: SA type-2 semantics (λ scales the term's mean).
      g: optional λ transform for residual terms (``g_MSE``).
      data_X / data_s: optional assimilation observations.
      residual_fn: optional fused batched residual ``(params, X) -> preds``
        (one Taylor wavefront, :mod:`tensordiffeq_tpu.ops.fused`); the
        generic per-point engine is used when ``None``.
      residual_loss_fn: optional fused *residual-loss* term
        ``(params, lam_res, X) -> scalar`` replacing the whole
        residual-evaluation + λ-weighting + reduction block with one fused
        unit (the minimax engine,
        :mod:`tensordiffeq_tpu.ops.pallas_minimax` — the per-term λ list
        routes one channel per residual equation, this function's λ
        semantics reproduced per channel inside the fusion; an E-equation
        system reports as a single ``Residual_0`` component equal to the
        Σ over the generic engine's per-equation terms).
        Takes precedence over ``residual_fn`` for the residual term;
        incompatible with ``causal_eps`` (cross-point bin weighting cannot
        live inside the per-point fusion) — the solver gates on that.
      causal_eps / causal_bins / time_index / time_bounds: temporal
        causality weighting of the residual terms
        (:func:`~tensordiffeq_tpu.ops.losses.causal_residual_loss`) —
        enabled when ``causal_eps`` is set; ``time_index`` is the time
        column of ``X_batch`` and ``time_bounds`` its range.  Composes
        with per-point SA λ (applied inside the bin means).
      remat: rematerialize the residual evaluation in the backward pass
        (``jax.checkpoint``).  The residual's higher-order derivative
        chain is the memory-dominant intermediate at large ``N_f`` —
        several activation-sized buffers per Taylor/jvp order, all live
        until the backward pass — and on TPU the HBM ceiling, not FLOPs,
        caps points-per-chip.  Rematerialization stores only the inputs
        and recomputes the chain during backward: peak memory drops by
        roughly the chain multiplicity for one extra forward evaluation
        of FLOPs (the classic compute-for-HBM trade).  Identical maths;
        pair with ``fit(batch_sz=)`` to push ``N_f`` further.

    Returns a pure function
    ``loss(params, lam_bcs, lam_res, X_batch, lam_data=None) ->
    (total, components)`` where ``lam_bcs``/``lam_res`` are per-term lists
    (``None`` = non-adaptive), ``lam_data`` is an optional scalar weight on
    the assimilation term (NTK balancing), and ``components`` is the
    reference's per-epoch loss dict
    (``BC_i`` / ``Residual_i`` / ``Total Loss``, ``models.py:117-216``).
    """
    ndim = len(varnames)

    # Freeze BC host data as device constants once.
    frozen = []
    for bc in bcs:
        if bc.isPeriodic:
            frozen.append(("periodic",
                           [jnp.asarray(p, jnp.float32) for p in bc.upper],
                           [jnp.asarray(p, jnp.float32) for p in bc.lower],
                           list(bc.deriv_model)))
        elif bc.isNeumann:
            frozen.append(("neumann",
                           [jnp.asarray(p, jnp.float32) for p in bc.input],
                           [jnp.asarray(v, jnp.float32) for v in bc.val],
                           list(bc.deriv_model)))
        elif bc.isInit or bc.isDirichlet or bc.isDirichlect:
            frozen.append(("value",
                           jnp.asarray(bc.input, jnp.float32),
                           jnp.asarray(bc.val, jnp.float32),
                           None))
        else:
            raise ValueError(f"Unsupported boundary condition: {bc!r}")

    if data_X is not None:
        data_X = jnp.asarray(data_X, jnp.float32)
        data_s = jnp.asarray(data_s, jnp.float32)

    def _residual_eval(params, X_batch):
        if residual_fn is not None:
            return residual_fn(params, X_batch)
        u_local = make_ufn(apply_fn, params, varnames, n_out)
        return vmap_residual(f_model, u_local, ndim)(X_batch)

    if remat:
        _residual_eval = jax.checkpoint(_residual_eval)

    def loss(params, lam_bcs, lam_res, X_batch, lam_data=None):
        u = make_ufn(apply_fn, params, varnames, n_out)
        components: dict[str, jnp.ndarray] = {}

        loss_bcs = 0.0
        for i, (kind, a, b, derivs) in enumerate(frozen):
            lam = lam_bcs[i] if i < len(lam_bcs) else None
            if kind == "value":
                pred = apply_fn(params, a)
                loss_bc = MSE(pred, b, lam, weight_outside_sum)
            elif kind == "periodic":
                loss_bc = 0.0
                for upper_pts, lower_pts, dfn in zip(a, b, derivs):
                    ups = _vmap_deriv(dfn, u, upper_pts)
                    los = _vmap_deriv(dfn, u, lower_pts)
                    for up, lo in zip(ups, los):
                        loss_bc += MSE(up, lo)
                # scalar term weight (NTK weighting reaches periodic BCs;
                # user-provided per-point λ is rejected upstream)
                if lam is not None and weight_outside_sum:
                    loss_bc = jnp.reshape(lam, ()) * loss_bc
            else:  # neumann — derivative on each var's face vs its own target
                loss_bc = 0.0
                for inp_pts, val_i, dfn in zip(a, b, derivs):
                    vals = _vmap_deriv(dfn, u, inp_pts)
                    for comp in vals:
                        loss_bc += MSE(val_i, comp.reshape(val_i.shape))
                if lam is not None and weight_outside_sum:
                    loss_bc = jnp.reshape(lam, ()) * loss_bc
            components[f"BC_{i}"] = loss_bc
            loss_bcs = loss_bcs + loss_bc

        if residual_loss_fn is not None:
            # the fused minimax unit: residual + λ weighting + reduction
            # (and, under AD, every cotangent) in one fusion — the whole
            # system residual (Σ over equations) reports as one component
            loss_res = residual_loss_fn(params, lam_res, X_batch)
            components["Residual_0"] = loss_res
            f_preds = ()
        else:
            f_preds = _as_tuple(_residual_eval(params, X_batch))
            loss_res = 0.0
        for j, f_pred in enumerate(f_preds):
            f_pred = f_pred.reshape(-1, 1)
            lam = lam_res[j] if j < len(lam_res) else None
            if causal_eps is not None:
                # per-point squared errors with λ folded in EXACTLY as the
                # non-causal path below would (g_MSE applies g(λ) per-point
                # regardless of weight_outside_sum; type-2 scalar λ scales
                # the whole term), then causality-weighted bin means
                outer = None
                if lam is not None and g is not None:
                    sq = g(lam) * jnp.square(f_pred)       # g_MSE semantics
                elif lam is not None and not weight_outside_sum:
                    sq = jnp.square(lam * f_pred)          # SA type-1
                else:
                    sq = jnp.square(f_pred)
                    outer = lam                            # type-2 scalar
                loss_r, w_last = causal_residual_loss(
                    sq, X_batch[:, time_index], time_bounds,
                    causal_eps, causal_bins)
                if outer is not None:
                    loss_r = jnp.reshape(outer, ()) * loss_r
                components[f"Causal_w_last_{j}"] = w_last
            elif lam is not None:
                if g is not None:
                    loss_r = g_MSE(f_pred, 0.0, g(lam))
                else:
                    loss_r = MSE(f_pred, 0.0, lam, weight_outside_sum)
            else:
                loss_r = MSE(f_pred, 0.0)
            components[f"Residual_{j}"] = loss_r
            loss_res = loss_res + loss_r

        total = loss_bcs + loss_res

        if data_X is not None:
            loss_data = MSE(apply_fn(params, data_X), data_s)
            if lam_data is not None:  # scalar NTK balancing weight
                loss_data = jnp.reshape(lam_data, ()) * loss_data
            components["Data"] = loss_data
            total = total + loss_data

        components["Total Loss"] = total
        return total, components

    return loss
