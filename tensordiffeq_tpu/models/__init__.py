"""Solver APIs: forward collocation and inverse discovery models."""

from .collocation import CollocationSolverND  # noqa: F401
from .discovery import DiscoveryModel  # noqa: F401

__all__ = ["CollocationSolverND", "DiscoveryModel"]
