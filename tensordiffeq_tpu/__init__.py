"""TensorDiffEq-TPU: a TPU-native (JAX/XLA) physics-informed neural network
framework with the capabilities of TensorDiffEq (reference:
``tensordiffeq/__init__.py:3-24`` namespace parity).

Quick start (Burgers)::

    import numpy as np
    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import DomainND, IC, dirichletBC, CollocationSolverND, grad

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], 256)
    domain.add("t", [0.0, 1.0], 100)
    domain.generate_collocation_points(10_000, seed=0)

    init = IC(domain, [lambda x: -np.sin(np.pi * x)], var=[["x"]])
    bcs = [init,
           dirichletBC(domain, val=0.0, var="x", target="upper"),
           dirichletBC(domain, val=0.0, var="x", target="lower")]

    def f_model(u, x, t):
        u_x, u_t = grad(u, "x"), grad(u, "t")
        u_xx = grad(u_x, "x")
        return u_t(x, t) + u(x, t) * u_x(x, t) - (0.01 / np.pi) * u_xx(x, t)

    solver = CollocationSolverND()
    solver.compile([2, 20, 20, 20, 20, 1], f_model, domain, bcs)
    solver.fit(tf_iter=10_000, newton_iter=10_000)
"""

from . import boundaries, checkpoint, domains, exact, helpers  # noqa: F401
from . import networks, ops, output  # noqa: F401
from . import parallel, plotting, profiling, sampling, telemetry  # noqa: F401
from . import resilience, training, utils  # noqa: F401
from . import factory, fleet, models, serving, zoo  # noqa: F401
from .boundaries import (  # noqa: F401
    BC, IC, FunctionDirichletBC, FunctionNeumannBC, dirichletBC, periodicBC)
from .domains import DomainND  # noqa: F401
from .helpers import find_L2_error  # noqa: F401
from .models import CollocationSolverND, DiscoveryModel  # noqa: F401
from .networks import (MLP, FourierMLP, PeriodicMLP, fourier_net,  # noqa: F401
                       neural_net, periodic_net)
from .ops import (MSE, UFn, d, g_MSE, grad, laplacian,  # noqa: F401
                  set_default_grad_mode)
from .resilience import (Chaos, CircuitBreaker, Preempted,  # noqa: F401
                         PreemptionHandler, ResilientFit, RetryPolicy)
from .factory import SurrogateFactory  # noqa: F401
from .fleet import (AdmissionController, AdmissionRejected,  # noqa: F401
                    FleetRouter, TenantPolicy)
from .serving import (ArtifactVersionMismatch, InferenceEngine,  # noqa: F401
                      RequestBatcher, Surrogate)
from .telemetry import (MetricsRegistry, RunLogger,  # noqa: F401
                        TrainingDiverged, TrainingTelemetry)

__version__ = "0.3.0"  # kept in sync with pyproject.toml
