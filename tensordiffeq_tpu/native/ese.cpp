// Native ESE maximin-LHS optimizer.
//
// C++ implementation of the Enhanced Stochastic Evolutionary algorithm
// (Jin, Chen & Sudjianto 2005) used for the LHS 'ese' criterion — the
// capability the reference vendors from SMT (reference sampling.py:315-534).
// The annealing loop is O(outer * inner * J * n * nx) scalar work on the
// host; this native version exists because the pure-NumPy fallback in
// ../sampling.py is orders of magnitude slower at large point counts
// (N_f up to 500,000 in the reference's distributed config,
// examples/AC-dist-new.py:14).
//
// Algorithmically identical to sampling._maximin_ese (same proposal scheme,
// acceptance rule and temperature adaptation); RNG streams differ, so
// results are deterministic per seed but not bit-identical across the two
// implementations.
//
// C ABI only (consumed via ctypes — no pybind11 in this image).

#include <cmath>
#include <cstdint>
#include <random>
#include <vector>

namespace {

// Sum of d_ij^-p over all pairs (the "PhiP power sum"); phi = sum^(1/p).
double phi_p_pow_sum(const double* X, int n, int nx, double p) {
    double s = 0.0;
    for (int i = 0; i < n; ++i) {
        const double* xi = X + (std::size_t)i * nx;
        for (int j = i + 1; j < n; ++j) {
            const double* xj = X + (std::size_t)j * nx;
            double d2 = 0.0;
            for (int k = 0; k < nx; ++k) {
                double diff = xi[k] - xj[k];
                d2 += diff * diff;
            }
            s += std::pow(d2, -0.5 * p);
        }
    }
    return s;
}

// Change in the PhiP power sum if rows i1/i2 swapped their column-k values.
// O(n * nx): only distances involving rows i1 and i2 change.
double swap_delta(const double* X, int n, int nx, double p,
                  int k, int i1, int i2) {
    const double* a = X + (std::size_t)i1 * nx;
    const double* b = X + (std::size_t)i2 * nx;
    const double ak_new = b[k], bk_new = a[k];
    double delta = 0.0;
    for (int j = 0; j < n; ++j) {
        if (j == i1 || j == i2) continue;
        const double* xj = X + (std::size_t)j * nx;
        double d2a_old = 0.0, d2b_old = 0.0;
        for (int c = 0; c < nx; ++c) {
            double da = a[c] - xj[c];
            double db = b[c] - xj[c];
            d2a_old += da * da;
            d2b_old += db * db;
        }
        double da_k_old = a[k] - xj[k], db_k_old = b[k] - xj[k];
        double da_k_new = ak_new - xj[k], db_k_new = bk_new - xj[k];
        double d2a_new = d2a_old - da_k_old * da_k_old + da_k_new * da_k_new;
        double d2b_new = d2b_old - db_k_old * db_k_old + db_k_new * db_k_new;
        delta += std::pow(d2a_new, -0.5 * p) - std::pow(d2a_old, -0.5 * p)
               + std::pow(d2b_new, -0.5 * p) - std::pow(d2b_old, -0.5 * p);
    }
    // Distance between i1 and i2 themselves is invariant under the swap
    // (both coordinates exchange, preserving their difference's magnitude).
    return delta;
}

}  // namespace

extern "C" {

double tdq_phi_p(const double* X, int n, int nx, double p) {
    if (n < 2) return 0.0;
    return std::pow(phi_p_pow_sum(X, n, nx, p), 1.0 / p);
}

// In-place ESE optimization of an [n, nx] row-major unit-cube LHS design.
// Returns the best PhiP reached; X holds the best design on exit.
double tdq_ese_optimize(double* X, int n, int nx, double p,
                        int outer_loops, int inner_loops, int J,
                        uint64_t seed) {
    if (n < 3 || nx < 1) return tdq_phi_p(X, n, nx, p);

    std::mt19937_64 rng(seed);
    std::uniform_real_distribution<double> unif(0.0, 1.0);
    std::uniform_int_distribution<int> row(0, n - 1);

    double S = phi_p_pow_sum(X, n, nx, p);        // current power sum
    double phi = std::pow(S, 1.0 / p);
    double phi_best = phi;
    std::vector<double> X_best(X, X + (std::size_t)n * nx);
    double T = 0.005 * phi;

    for (int outer = 0; outer < outer_loops; ++outer) {
        int n_accept = 0, n_improve = 0;
        for (int inner = 0; inner < inner_loops; ++inner) {
            int k = inner % nx;
            // best of J random row-swap proposals in column k
            double best_delta = 0.0, best_phi = 0.0;
            int best_i1 = -1, best_i2 = -1;
            bool have = false;
            for (int t = 0; t < J; ++t) {
                int i1 = row(rng), i2 = row(rng);
                while (i2 == i1) i2 = row(rng);
                double delta = swap_delta(X, n, nx, p, k, i1, i2);
                double S_try = S + delta;
                if (S_try < 0.0) S_try = 0.0;
                double phi_try = std::pow(S_try, 1.0 / p);
                if (!have || phi_try < best_phi) {
                    have = true;
                    best_phi = phi_try;
                    best_delta = delta;
                    best_i1 = i1;
                    best_i2 = i2;
                }
            }
            if (best_phi - phi <= T * unif(rng)) {
                double* r1 = X + (std::size_t)best_i1 * nx;
                double* r2 = X + (std::size_t)best_i2 * nx;
                std::swap(r1[k], r2[k]);
                S += best_delta;
                if (S < 0.0) S = 0.0;
                phi = best_phi;
                ++n_accept;
                if (phi < phi_best) {
                    phi_best = phi;
                    X_best.assign(X, X + (std::size_t)n * nx);
                    ++n_improve;
                }
            }
        }
        // temperature adaptation (Jin et al. section 3.2)
        double acc = (double)n_accept / inner_loops;
        double imp = (double)n_improve / inner_loops;
        if (imp < 0.1) {
            T = (acc > 0.1) ? T * 0.8 : T / 0.7;
        } else {
            T = (acc > imp) ? T * 0.9 : T / 0.9;
        }
    }

    std::copy(X_best.begin(), X_best.end(), X);
    return phi_best;
}

}  // extern "C"
