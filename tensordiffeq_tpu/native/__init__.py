"""Native (C++) host-side runtime components, bound via ctypes.

The TPU compute path is JAX/XLA; host-side setup work that is scalar-loop
heavy lives here instead.  Currently: the ESE maximin-LHS annealing
optimizer (see ``ese.cpp``), replacing the reference's vendored-SMT Python
implementation (reference ``sampling.py:315-534``) with a compiled one.

The shared library is built lazily with ``g++`` on first use and cached
next to the source (keyed on source mtime).  Everything degrades
gracefully: if no toolchain is available, callers fall back to the pure
NumPy implementation in :mod:`tensordiffeq_tpu.sampling`.  Set
``TDQ_NO_NATIVE=1`` to force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "ese.cpp")
_LIB = os.path.join(_DIR, "_ese.so")

_lock = threading.Lock()
_lib = None
_load_failed = False


def _build() -> None:
    # compile to a process-unique temp path, then atomically rename: two
    # processes racing the first build must never interleave writes into
    # the cached .so
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
           "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _LIB)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


class NativeUnavailable(RuntimeError):
    """The native C++ kernel could not be loaded (no compiler, build
    error, or opt-out) and the caller did not fall back — typed so
    callers can catch exactly this and choose the pure-numpy path."""

    trace_id = None

    def __init__(self):
        super().__init__("native library unavailable")


def load():
    """Return the loaded ctypes library, building it if needed, or ``None``
    when native support is unavailable (no compiler, build error, opt-out)."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed or os.environ.get("TDQ_NO_NATIVE") == "1":
        return None
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            stale = (not os.path.exists(_LIB)
                     or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
            if stale:
                _build()
            lib = ctypes.CDLL(_LIB)
            lib.tdq_phi_p.restype = ctypes.c_double
            lib.tdq_phi_p.argtypes = [
                ctypes.POINTER(ctypes.c_double), ctypes.c_int, ctypes.c_int,
                ctypes.c_double]
            lib.tdq_ese_optimize.restype = ctypes.c_double
            lib.tdq_ese_optimize.argtypes = [
                ctypes.POINTER(ctypes.c_double), ctypes.c_int, ctypes.c_int,
                ctypes.c_double, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_uint64]
            _lib = lib
        except (OSError, subprocess.CalledProcessError) as e:
            _load_failed = True
            from ..telemetry import log_event
            log_event("tdq.native", f"C++ ESE unavailable ({e}); "
                      "using NumPy fallback", level="warning")
    return _lib


def available() -> bool:
    return load() is not None


def phi_p(X: np.ndarray, p: float = 10.0) -> float:
    """PhiP space-filling criterion via the native kernel."""
    lib = load()
    if lib is None:
        raise NativeUnavailable()
    X = np.ascontiguousarray(X, dtype=np.float64)
    return lib.tdq_phi_p(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        X.shape[0], X.shape[1], p)


def ese_optimize(X: np.ndarray, p: float = 10.0,
                 outer_loops: int = 30, inner_loops: int = 20, J: int = 10,
                 seed: int = 0) -> np.ndarray:
    """ESE maximin optimization of a unit-cube LHS design (copy returned).

    Mirrors :func:`tensordiffeq_tpu.sampling._maximin_ese`'s algorithm; see
    ``ese.cpp`` for the annealing details.
    """
    lib = load()
    if lib is None:
        raise NativeUnavailable()
    out = np.ascontiguousarray(X, dtype=np.float64).copy()
    lib.tdq_ese_optimize(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out.shape[0], out.shape[1], p, outer_loops, inner_loops, J,
        np.uint64(seed))
    return out
