"""High-accuracy reference solutions for the canonical benchmark PDEs.

The reference ships binary fixtures (``examples/AC.mat`` — a 512x201
Allen-Cahn spectral solution loaded at ``examples/AC-baseline.py:55`` — and
``examples/burgers_shock.mat``, ``examples/burgers-new.py:48``) but not the
code that produced them.  Here the fixtures are *generated*, reproducibly:

* :func:`allen_cahn_solution` — Fourier pseudo-spectral discretisation +
  ETDRK4 exponential time integrator (Kassam & Trefethen 2005) for
  ``u_t = 1e-4 u_xx + 5(u - u^3)`` with periodic BCs on x in [-1, 1].
* :func:`burgers_solution` — the Cole–Hopf closed form for
  ``u_t + u u_x = nu u_xx``, ``u(x,0) = -sin(pi x)``, evaluated with
  Gauss–Hermite quadrature (the classical evaluation used by Basdevant et
  al. 1986 for exactly this nu = 0.01/pi shock benchmark).

Solutions are memoised to ``.npz`` files under a cache directory so tests,
examples and ``bench.py`` pay the (CPU, seconds-scale) cost once.

The PDE-zoo entries (PR 17, :mod:`tensordiffeq_tpu.zoo`) add CLOSED-FORM
references — evaluated directly, no memoisation needed:

* :func:`taylor_green_solution` — the decaying Taylor–Green vortex, the
  exact unsteady incompressible Navier–Stokes solution (u, v, p).
* :func:`reaction_diffusion_solution` — a rotation-coupled linear
  2-component reaction–diffusion system, single Fourier mode (the matrix
  exponential is analytic for equal diffusivities).
* :func:`heat3d_solution` — the separable 3D heat-equation mode.
* :func:`convection_solution` — pure advection of a periodic profile
  (the stiff convection-dominated benchmark of arXiv:2109.01050).
"""

from __future__ import annotations

import os

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(__file__), "_fixture_cache")


def _cache_path(name: str) -> str:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    return os.path.join(_CACHE_DIR, name + ".npz")


def _memoise(name, builder):
    path = _cache_path(name)
    if os.path.exists(path):
        with np.load(path) as z:
            return z["x"], z["t"], z["u"]
    x, t, u = builder()
    np.savez_compressed(path, x=x, t=t, u=u)
    return x, t, u


# --------------------------------------------------------------------------- #
# Allen-Cahn: Fourier spectral + ETDRK4
# --------------------------------------------------------------------------- #
def _etdrk4_allen_cahn(nx: int, nt: int, t_final: float, eps: float,
                       dt: float):
    """Integrate u_t = eps*u_xx + 5u - 5u^3, periodic on [-1, 1)."""
    x = -1.0 + 2.0 * np.arange(nx) / nx           # periodic grid (no endpoint)
    u = x ** 2 * np.cos(np.pi * x)                # reference IC (AC-SA paper)
    v = np.fft.fft(u)

    # wavenumbers for period L = 2
    k = np.fft.fftfreq(nx, d=1.0 / nx) * np.pi    # 2*pi*m/L with L=2
    L = -eps * k ** 2 + 5.0                       # linear operator symbol
    E = np.exp(dt * L)
    E2 = np.exp(dt * L / 2.0)

    # ETDRK4 scalar coefficients via complex contour integral (Kassam-Trefethen)
    M = 32
    r = np.exp(1j * np.pi * (np.arange(1, M + 1) - 0.5) / M)
    LR = dt * L[:, None] + r[None, :]
    Q = dt * np.real(np.mean((np.exp(LR / 2) - 1) / LR, axis=1))
    f1 = dt * np.real(np.mean(
        (-4 - LR + np.exp(LR) * (4 - 3 * LR + LR ** 2)) / LR ** 3, axis=1))
    f2 = dt * np.real(np.mean(
        (2 + LR + np.exp(LR) * (-2 + LR)) / LR ** 3, axis=1))
    f3 = dt * np.real(np.mean(
        (-4 - 3 * LR - LR ** 2 + np.exp(LR) * (4 - LR)) / LR ** 3, axis=1))

    def N(vhat):
        uu = np.real(np.fft.ifft(vhat))
        return np.fft.fft(-5.0 * uu ** 3)

    n_steps = int(round(t_final / dt))
    save_every = max(1, n_steps // (nt - 1))
    # adjust dt so that n_steps is an exact multiple of (nt - 1)
    assert n_steps % (nt - 1) == 0, "choose dt dividing t_final/(nt-1)"

    out = np.empty((nx, nt))
    out[:, 0] = u
    j = 1
    for n in range(1, n_steps + 1):
        Nv = N(v)
        a = E2 * v + Q * Nv
        Na = N(a)
        b = E2 * v + Q * Na
        Nb = N(b)
        c = E2 * a + Q * (2 * Nb - Nv)
        Nc = N(c)
        v = E * v + Nv * f1 + 2 * (Na + Nb) * f2 + Nc * f3
        if n % save_every == 0:
            out[:, j] = np.real(np.fft.ifft(v))
            j += 1
    assert j == nt
    return x, out


def allen_cahn_solution(nx: int = 512, nt: int = 201, t_final: float = 1.0,
                        eps: float = 1e-4):
    """Allen-Cahn benchmark solution on a ``(nx, nt)`` grid.

    Returns ``(x, t, usol)`` with ``x`` shape (nx,), ``t`` shape (nt,),
    ``usol`` shape (nx, nt) — same layout as the reference's ``AC.mat``
    (``examples/AC-baseline.py:55-63``).
    """
    def build():
        # dt = t_final / (k*(nt-1)) with enough substeps for ETDRK4 accuracy
        substeps = 10  # 2000 total steps: well inside ETDRK4's stability
        dt = t_final / ((nt - 1) * substeps)
        x, u = _etdrk4_allen_cahn(nx, nt, t_final, eps, dt)
        t = np.linspace(0.0, t_final, nt)
        return x, t, u

    return _memoise(f"allen_cahn_{nx}x{nt}_{eps:g}", build)


# --------------------------------------------------------------------------- #
# Burgers: Cole-Hopf with Gauss-Hermite quadrature
# --------------------------------------------------------------------------- #
def burgers_solution(nx: int = 256, nt: int = 100, nu: float = 0.01 / np.pi,
                     n_quad: int = 100):
    """Exact viscous-Burgers solution ``u_t + u u_x = nu u_xx`` with
    ``u(x, 0) = -sin(pi x)`` on [-1, 1] (homogeneous Dirichlet by symmetry).

    Cole–Hopf:  u(x,t) = -∫ sin(pi(x-z)) f(x-z) G(z) dz / ∫ f(x-z) G(z) dz
    with f(y) = exp(-cos(pi y)/(2 pi nu)), G the heat kernel; substituting
    z = sqrt(4 nu t) s gives Gauss–Hermite form.  Returns ``(x, t, usol)``
    with ``usol`` shape (nx, nt); t starts at 0 (IC row exact).
    """
    def build():
        x = np.linspace(-1.0, 1.0, nx)
        t = np.linspace(0.0, 1.0, nt)
        s_nodes, s_weights = np.polynomial.hermite.hermgauss(n_quad)
        u = np.empty((nx, nt))
        u[:, 0] = -np.sin(np.pi * x)
        c = 1.0 / (2.0 * np.pi * nu)
        for j, tj in enumerate(t[1:], start=1):
            a = np.sqrt(4.0 * nu * tj)
            # y[i, q] = x_i - a*s_q
            y = x[:, None] - a * s_nodes[None, :]
            f = np.exp(-c * np.cos(np.pi * y))
            num = -(np.sin(np.pi * y) * f) @ s_weights
            den = f @ s_weights
            u[:, j] = num / den
        return x, t, u

    return _memoise(f"burgers_{nx}x{nt}_{nu:g}_{n_quad}", build)


# --------------------------------------------------------------------------- #
# Nonlinear Schrödinger: split-step Fourier (Strang splitting)
# --------------------------------------------------------------------------- #
def schrodinger_solution(nx: int = 256, nt: int = 201,
                         t_final: float = np.pi / 2, substeps: int = 20):
    """Focusing NLS benchmark ``i h_t + 0.5 h_xx + |h|^2 h = 0`` with
    ``h(x, 0) = 2 sech(x)``, periodic on x in [-5, 5) — the classical
    2-output (real/imaginary) PINN benchmark (Raissi et al. 2019 §3.1.1;
    the reference framework handles 2-output residual tuples at
    ``models.py:189-191`` but ships no such example).

    Strang split-step Fourier: the nonlinear phase rotation
    ``h <- exp(i |h|^2 dt) h`` is exact (|h| invariant), the linear step is
    exact in Fourier space, so the scheme is spectrally accurate in x and
    O(dt^2) in t.  Returns ``(x, t, h)`` with complex ``h`` of shape
    ``(nx, nt)``.
    """
    def build():
        x = -5.0 + 10.0 * np.arange(nx) / nx      # periodic grid, L = 10
        t = np.linspace(0.0, t_final, nt)
        k = np.fft.fftfreq(nx, d=1.0 / nx) * (2.0 * np.pi / 10.0)
        dt = t_final / ((nt - 1) * substeps)
        half_lin = np.exp(-0.5j * k ** 2 * (dt / 2.0))

        h = (2.0 / np.cosh(x)).astype(np.complex128)
        out = np.empty((nx, nt), dtype=np.complex128)
        out[:, 0] = h
        for j in range(1, nt):
            for _ in range(substeps):
                h = np.fft.ifft(half_lin * np.fft.fft(h))
                h = h * np.exp(1j * np.abs(h) ** 2 * dt)
                h = np.fft.ifft(half_lin * np.fft.fft(h))
            out[:, j] = h
        return x, t, out

    return _memoise(f"schrodinger_{nx}x{nt}_{t_final:g}_{substeps}", build)


# --------------------------------------------------------------------------- #
# Closed-form references for the PDE zoo (no memoisation: evaluation is
# vectorised NumPy over the requested grid, milliseconds even in 3D+t)
# --------------------------------------------------------------------------- #
def taylor_green_solution(nx: int = 32, ny: int = 32, nt: int = 11,
                          nu: float = 0.1, t_final: float = 1.0):
    """Decaying Taylor–Green vortex on ``[0, pi]^2`` — the classical exact
    solution of the unsteady incompressible Navier–Stokes equations::

        u(x,y,t) = -cos(x) sin(y) e^{-2 nu t}
        v(x,y,t) =  sin(x) cos(y) e^{-2 nu t}
        p(x,y,t) = -(cos(2x) + cos(2y))/4 e^{-4 nu t}

    Returns ``(x, y, t, uvp)`` with ``uvp`` of shape ``(nx, ny, nt, 3)``
    (components stacked last: u, v, p).
    """
    x = np.linspace(0.0, np.pi, nx)
    y = np.linspace(0.0, np.pi, ny)
    t = np.linspace(0.0, t_final, nt)
    X, Y, T = np.meshgrid(x, y, t, indexing="ij")
    decay = np.exp(-2.0 * nu * T)
    u = -np.cos(X) * np.sin(Y) * decay
    v = np.sin(X) * np.cos(Y) * decay
    p = -0.25 * (np.cos(2.0 * X) + np.cos(2.0 * Y)) * decay ** 2
    return x, y, t, np.stack([u, v, p], axis=-1)


def reaction_diffusion_solution(nx: int = 64, nt: int = 33, d: float = 0.1,
                                a: float = np.pi, t_final: float = 1.0):
    """Rotation-coupled linear reaction–diffusion system on ``[0, pi]``::

        u_t = d u_xx + a v        u(x,0) = sin(x)
        v_t = d v_xx - a u        v(x,0) = 0

    with homogeneous Dirichlet BCs.  For equal diffusivities the matrix
    exponential of the single ``k=1`` Fourier mode is exact::

        u = e^{-d t} cos(a t) sin(x),   v = -e^{-d t} sin(a t) sin(x)

    Returns ``(x, t, uv)`` with ``uv`` of shape ``(nx, nt, 2)``.
    """
    x = np.linspace(0.0, np.pi, nx)
    t = np.linspace(0.0, t_final, nt)
    X, T = np.meshgrid(x, t, indexing="ij")
    decay = np.exp(-d * T)
    u = decay * np.cos(a * T) * np.sin(X)
    v = -decay * np.sin(a * T) * np.sin(X)
    return x, t, np.stack([u, v], axis=-1)


def heat3d_solution(n: int = 12, nt: int = 9, kappa: float = 0.05,
                    t_final: float = 1.0):
    """Separable 3D heat-equation mode ``u_t = kappa lap(u)`` on the unit
    cube with homogeneous Dirichlet BCs::

        u = sin(pi x) sin(pi y) sin(pi z) e^{-3 pi^2 kappa t}

    Returns ``(x, y, z, t, u)`` with ``u`` of shape ``(n, n, n, nt)``.
    """
    x = y = z = np.linspace(0.0, 1.0, n)
    t = np.linspace(0.0, t_final, nt)
    X, Y, Z, T = np.meshgrid(x, y, z, t, indexing="ij")
    u = (np.sin(np.pi * X) * np.sin(np.pi * Y) * np.sin(np.pi * Z)
         * np.exp(-3.0 * np.pi ** 2 * kappa * T))
    return x, y, z, t, u


def convection_solution(nx: int = 128, nt: int = 65, beta: float = 10.0,
                        t_final: float = 1.0):
    """Pure advection ``u_t + beta u_x = 0`` of ``u(x,0) = sin(x)``,
    periodic on ``[0, 2 pi)`` — the convection-dominated benchmark where
    vanilla PINNs famously stall as ``beta`` grows (arXiv:2109.01050)::

        u(x, t) = sin(x - beta t)

    Returns ``(x, t, u)`` with ``u`` of shape ``(nx, nt)``.
    """
    x = 2.0 * np.pi * np.arange(nx) / nx
    t = np.linspace(0.0, t_final, nt)
    X, T = np.meshgrid(x, t, indexing="ij")
    return x, t, np.sin(X - beta * T)
