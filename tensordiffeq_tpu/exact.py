"""High-accuracy reference solutions for the canonical benchmark PDEs.

The reference ships binary fixtures (``examples/AC.mat`` — a 512x201
Allen-Cahn spectral solution loaded at ``examples/AC-baseline.py:55`` — and
``examples/burgers_shock.mat``, ``examples/burgers-new.py:48``) but not the
code that produced them.  Here the fixtures are *generated*, reproducibly:

* :func:`allen_cahn_solution` — Fourier pseudo-spectral discretisation +
  ETDRK4 exponential time integrator (Kassam & Trefethen 2005) for
  ``u_t = 1e-4 u_xx + 5(u - u^3)`` with periodic BCs on x in [-1, 1].
* :func:`burgers_solution` — the Cole–Hopf closed form for
  ``u_t + u u_x = nu u_xx``, ``u(x,0) = -sin(pi x)``, evaluated with
  Gauss–Hermite quadrature (the classical evaluation used by Basdevant et
  al. 1986 for exactly this nu = 0.01/pi shock benchmark).

Solutions are memoised to ``.npz`` files under a cache directory so tests,
examples and ``bench.py`` pay the (CPU, seconds-scale) cost once.
"""

from __future__ import annotations

import os

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(__file__), "_fixture_cache")


def _cache_path(name: str) -> str:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    return os.path.join(_CACHE_DIR, name + ".npz")


def _memoise(name, builder):
    path = _cache_path(name)
    if os.path.exists(path):
        with np.load(path) as z:
            return z["x"], z["t"], z["u"]
    x, t, u = builder()
    np.savez_compressed(path, x=x, t=t, u=u)
    return x, t, u


# --------------------------------------------------------------------------- #
# Allen-Cahn: Fourier spectral + ETDRK4
# --------------------------------------------------------------------------- #
def _etdrk4_allen_cahn(nx: int, nt: int, t_final: float, eps: float,
                       dt: float):
    """Integrate u_t = eps*u_xx + 5u - 5u^3, periodic on [-1, 1)."""
    x = -1.0 + 2.0 * np.arange(nx) / nx           # periodic grid (no endpoint)
    u = x ** 2 * np.cos(np.pi * x)                # reference IC (AC-SA paper)
    v = np.fft.fft(u)

    # wavenumbers for period L = 2
    k = np.fft.fftfreq(nx, d=1.0 / nx) * np.pi    # 2*pi*m/L with L=2
    L = -eps * k ** 2 + 5.0                       # linear operator symbol
    E = np.exp(dt * L)
    E2 = np.exp(dt * L / 2.0)

    # ETDRK4 scalar coefficients via complex contour integral (Kassam-Trefethen)
    M = 32
    r = np.exp(1j * np.pi * (np.arange(1, M + 1) - 0.5) / M)
    LR = dt * L[:, None] + r[None, :]
    Q = dt * np.real(np.mean((np.exp(LR / 2) - 1) / LR, axis=1))
    f1 = dt * np.real(np.mean(
        (-4 - LR + np.exp(LR) * (4 - 3 * LR + LR ** 2)) / LR ** 3, axis=1))
    f2 = dt * np.real(np.mean(
        (2 + LR + np.exp(LR) * (-2 + LR)) / LR ** 3, axis=1))
    f3 = dt * np.real(np.mean(
        (-4 - 3 * LR - LR ** 2 + np.exp(LR) * (4 - LR)) / LR ** 3, axis=1))

    def N(vhat):
        uu = np.real(np.fft.ifft(vhat))
        return np.fft.fft(-5.0 * uu ** 3)

    n_steps = int(round(t_final / dt))
    save_every = max(1, n_steps // (nt - 1))
    # adjust dt so that n_steps is an exact multiple of (nt - 1)
    assert n_steps % (nt - 1) == 0, "choose dt dividing t_final/(nt-1)"

    out = np.empty((nx, nt))
    out[:, 0] = u
    j = 1
    for n in range(1, n_steps + 1):
        Nv = N(v)
        a = E2 * v + Q * Nv
        Na = N(a)
        b = E2 * v + Q * Na
        Nb = N(b)
        c = E2 * a + Q * (2 * Nb - Nv)
        Nc = N(c)
        v = E * v + Nv * f1 + 2 * (Na + Nb) * f2 + Nc * f3
        if n % save_every == 0:
            out[:, j] = np.real(np.fft.ifft(v))
            j += 1
    assert j == nt
    return x, out


def allen_cahn_solution(nx: int = 512, nt: int = 201, t_final: float = 1.0,
                        eps: float = 1e-4):
    """Allen-Cahn benchmark solution on a ``(nx, nt)`` grid.

    Returns ``(x, t, usol)`` with ``x`` shape (nx,), ``t`` shape (nt,),
    ``usol`` shape (nx, nt) — same layout as the reference's ``AC.mat``
    (``examples/AC-baseline.py:55-63``).
    """
    def build():
        # dt = t_final / (k*(nt-1)) with enough substeps for ETDRK4 accuracy
        substeps = 10  # 2000 total steps: well inside ETDRK4's stability
        dt = t_final / ((nt - 1) * substeps)
        x, u = _etdrk4_allen_cahn(nx, nt, t_final, eps, dt)
        t = np.linspace(0.0, t_final, nt)
        return x, t, u

    return _memoise(f"allen_cahn_{nx}x{nt}_{eps:g}", build)


# --------------------------------------------------------------------------- #
# Burgers: Cole-Hopf with Gauss-Hermite quadrature
# --------------------------------------------------------------------------- #
def burgers_solution(nx: int = 256, nt: int = 100, nu: float = 0.01 / np.pi,
                     n_quad: int = 100):
    """Exact viscous-Burgers solution ``u_t + u u_x = nu u_xx`` with
    ``u(x, 0) = -sin(pi x)`` on [-1, 1] (homogeneous Dirichlet by symmetry).

    Cole–Hopf:  u(x,t) = -∫ sin(pi(x-z)) f(x-z) G(z) dz / ∫ f(x-z) G(z) dz
    with f(y) = exp(-cos(pi y)/(2 pi nu)), G the heat kernel; substituting
    z = sqrt(4 nu t) s gives Gauss–Hermite form.  Returns ``(x, t, usol)``
    with ``usol`` shape (nx, nt); t starts at 0 (IC row exact).
    """
    def build():
        x = np.linspace(-1.0, 1.0, nx)
        t = np.linspace(0.0, 1.0, nt)
        s_nodes, s_weights = np.polynomial.hermite.hermgauss(n_quad)
        u = np.empty((nx, nt))
        u[:, 0] = -np.sin(np.pi * x)
        c = 1.0 / (2.0 * np.pi * nu)
        for j, tj in enumerate(t[1:], start=1):
            a = np.sqrt(4.0 * nu * tj)
            # y[i, q] = x_i - a*s_q
            y = x[:, None] - a * s_nodes[None, :]
            f = np.exp(-c * np.cos(np.pi * y))
            num = -(np.sin(np.pi * y) * f) @ s_weights
            den = f @ s_weights
            u[:, j] = num / den
        return x, t, u

    return _memoise(f"burgers_{nx}x{nt}_{nu:g}_{n_quad}", build)


# --------------------------------------------------------------------------- #
# Nonlinear Schrödinger: split-step Fourier (Strang splitting)
# --------------------------------------------------------------------------- #
def schrodinger_solution(nx: int = 256, nt: int = 201,
                         t_final: float = np.pi / 2, substeps: int = 20):
    """Focusing NLS benchmark ``i h_t + 0.5 h_xx + |h|^2 h = 0`` with
    ``h(x, 0) = 2 sech(x)``, periodic on x in [-5, 5) — the classical
    2-output (real/imaginary) PINN benchmark (Raissi et al. 2019 §3.1.1;
    the reference framework handles 2-output residual tuples at
    ``models.py:189-191`` but ships no such example).

    Strang split-step Fourier: the nonlinear phase rotation
    ``h <- exp(i |h|^2 dt) h`` is exact (|h| invariant), the linear step is
    exact in Fourier space, so the scheme is spectrally accurate in x and
    O(dt^2) in t.  Returns ``(x, t, h)`` with complex ``h`` of shape
    ``(nx, nt)``.
    """
    def build():
        x = -5.0 + 10.0 * np.arange(nx) / nx      # periodic grid, L = 10
        t = np.linspace(0.0, t_final, nt)
        k = np.fft.fftfreq(nx, d=1.0 / nx) * (2.0 * np.pi / 10.0)
        dt = t_final / ((nt - 1) * substeps)
        half_lin = np.exp(-0.5j * k ** 2 * (dt / 2.0))

        h = (2.0 / np.cosh(x)).astype(np.complex128)
        out = np.empty((nx, nt), dtype=np.complex128)
        out[:, 0] = h
        for j in range(1, nt):
            for _ in range(substeps):
                h = np.fft.ifft(half_lin * np.fft.fft(h))
                h = h * np.exp(1j * np.abs(h) ** 2 * dt)
                h = np.fft.ifft(half_lin * np.fft.fft(h))
            out[:, j] = h
        return x, t, out

    return _memoise(f"schrodinger_{nx}x{nt}_{t_final:g}_{substeps}", build)
