"""The closed loop: drift-triggered factory retraining with zero-downtime
hot-swap (ROADMAP item 4 — train → serve → monitor → retrain, autonomous).

Every prior fleet layer composes under a human operator; this module is
the operator.  Three pieces close the loop:

* :class:`DriftMonitor` — shadow-samples a configurable fraction of live
  ``u`` queries through the engine's EXISTING ``residual`` kind (one
  extra batched query; no new compiled programs, so the jaxpr audit's
  ``serving-residual`` pin already covers the probe path) and writes a
  per-tenant ``fleet.drift.level`` gauge: the windowed probe residual
  over the tenant's own attach-time baseline.  The gauge feeds the
  ``residual_drift`` objective of
  :class:`~tensordiffeq_tpu.telemetry.SLOSet` (``max_residual_drift``
  threshold; burn rate over the window; ``ok=None`` when nothing is
  monitored — absence of traffic is not a breach).  Monitoring is
  residual-as-supervision: the drift signal IS the self-supervision
  quantity the trainers optimize (arXiv:2207.04084), measured on the
  traffic the tenant actually serves.
* :class:`RetrainController` — when the monitor trips, retrains the
  drifting θ neighborhood as one
  :class:`~tensordiffeq_tpu.factory.SurrogateFactory` family
  **warm-started from the live members' served params**
  (``init_params=``) with drift-weighted collocation: the
  :class:`~tensordiffeq_tpu.ops.resampling.FamilyResampler` redraws each
  member's points by residual importance, concentrating the retrain
  exactly where the served residual (the drift) is largest — the
  importance-sampling rationale of arXiv:2104.12325.  The retrain runs
  under a supervisor loop in the
  :class:`~tensordiffeq_tpu.resilience.ClusterSupervisor` mold: a killed
  trainer (chaos ``retrain_kill_at``, or any organic
  :class:`~tensordiffeq_tpu.resilience.ChaosFault`-shaped death) is
  relaunched as a new generation with
  :class:`~tensordiffeq_tpu.resilience.RetryPolicy` backoff between
  launch attempts, resuming from the family's in-memory state exactly
  as the elastic supervisor resumes from the last checkpoint.
* :meth:`FleetRouter.hot_swap <tensordiffeq_tpu.fleet.FleetRouter.hot_swap>`
  — the v2 member artifact is loaded and warm-driven BESIDE the live
  tenant, canary-validated against the monitor's pinned probe set
  (replayed on old vs new engines), and only then does the route flip
  atomically: pending batches flushed, zero request-time compiles, zero
  dropped or hung waiters.  A candidate that fails its gate — or fails
  the artifact checksum (chaos ``swap_corrupt_member``) — is rejected
  and the old engine keeps serving, bit-validated (the probe replay
  after rollback is byte-compared against the pre-swap snapshot).

With no chaos active the monitored serve path is bit-identical to a
plain :class:`~tensordiffeq_tpu.fleet.FleetRouter` serve — the shadow
probe is a read-only residual query beside the ``u`` path
(``tests/test_closedloop.py`` pins this).
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Callable, Optional

import jax
import numpy as np

from ..resilience.chaos import ChaosFault, active_chaos
from ..resilience.retry import RetryPolicy
from ..telemetry import default_registry, log_event
from ..telemetry.slo import SLOSet
from ..telemetry.tracing import active_tracer, propagate_trace


class DriftMonitor:
    """Shadow-probe live traffic and turn served residual into an SLO.

    Args:
      router: the :class:`~tensordiffeq_tpu.fleet.FleetRouter` whose
        tenants are monitored.
      sample_fraction: fraction of observed ``u`` queries that get a
        shadow residual probe (seeded RNG — deterministic given the
        query sequence).  1.0 probes every query; 0.0 disables sampling
        (explicit :meth:`probe` calls still work).
      window: probes per tenant the drift level averages over (a burn
        window, not a single noisy probe).
      seed: sampling RNG seed.
      slo: the :class:`~tensordiffeq_tpu.telemetry.SLOSet` whose
        ``max_residual_drift`` threshold defines a trip (default: the
        standard set).
      registry: metrics destination (default: the shared process
        registry) — ``fleet.drift.*`` instruments land here, which is
        where :meth:`SLOSet.evaluate` reads the gauge back.
    """

    def __init__(self, router, *, sample_fraction: float = 0.25,
                 window: int = 4, seed: int = 0,
                 slo: Optional[SLOSet] = None, registry=None,
                 verbose: bool = False):
        if not 0.0 <= float(sample_fraction) <= 1.0:
            raise ValueError("sample_fraction must be in [0, 1], got "
                             f"{sample_fraction}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.router = router
        self.sample_fraction = float(sample_fraction)
        self.window = int(window)
        self.slo = slo if slo is not None else SLOSet.default()
        self.verbose = bool(verbose)
        self._registry = (registry if registry is not None
                          else default_registry())
        self._rng = np.random.RandomState(int(seed))
        self._baseline: dict = {}     # tenant -> attach-time mean |residual|
        self._probe_X: dict = {}      # tenant -> pinned probe set
        self._levels: dict = {}       # tenant -> deque of probe ratios
        self._tripped: dict = {}      # tenant -> sticky trip flag

    # ------------------------------------------------------------------ #
    def attach(self, tenant: str, probe_X) -> float:
        """Start monitoring ``tenant``: pin ``probe_X`` (the canary
        replay set) and record the attach-time baseline — one batched
        residual query through the live engine.  Returns the baseline
        mean absolute residual (the denominator of every later drift
        level)."""
        X = np.atleast_2d(np.asarray(probe_X, np.float32))
        lt = self.router.load(tenant)
        baseline = float(np.mean(np.abs(np.asarray(
            lt.engine.residual(X)))))
        if baseline <= 0.0:
            # an exactly-zero residual (untrained-net corner) cannot
            # serve as a ratio denominator; floor it so drift stays
            # finite instead of dividing by zero
            baseline = np.finfo(np.float32).tiny
        self._baseline[tenant] = baseline
        self._probe_X[tenant] = X
        self._levels[tenant] = deque(maxlen=self.window)
        self._tripped[tenant] = False
        self._registry.counter("fleet.drift.probes", tenant=tenant).inc()
        log_event("closedloop", f"monitoring tenant={tenant}: baseline "
                  f"|residual| {baseline:.3e} over {X.shape[0]} pinned "
                  "probe point(s)", verbose=self.verbose, event="attach",
                  tenant=str(tenant), baseline=baseline,
                  probe_points=int(X.shape[0]))
        return baseline

    def tenants(self) -> tuple:
        return tuple(self._baseline)

    def baseline(self, tenant: str) -> float:
        return self._baseline[tenant]

    def probe_set(self, tenant: str):
        """The pinned canary probe set recorded at attach time."""
        return self._probe_X[tenant]

    # ------------------------------------------------------------------ #
    def query(self, tenant: str, X, **kw):
        """Serve-and-observe convenience: the router's blocking
        :meth:`~tensordiffeq_tpu.fleet.FleetRouter.query` plus the
        shadow-sampling hook.  The ``u`` answer is untouched — with no
        chaos active it is bit-identical to the unmonitored call."""
        out = self.router.query(tenant, X, **kw)
        if kw.get("kind", "u") == "u":
            self.on_query(tenant, X)
        return out

    def on_query(self, tenant: str, X) -> Optional[float]:
        """Observe one live ``u`` query: with probability
        ``sample_fraction`` (seeded), shadow-probe the SAME points
        through the residual kind.  Returns the probe's drift level when
        one was taken, else None."""
        if tenant not in self._baseline:
            return None
        chaos = active_chaos()
        if chaos is not None:
            scale = chaos.on_drift_probe(tenant)
            if scale is not None:
                self._perturb_served_params(tenant, scale)
        if self.sample_fraction <= 0.0 \
                or self._rng.uniform() >= self.sample_fraction:
            return None
        return self.probe(tenant, X)

    def probe(self, tenant: str, X=None) -> float:
        """One shadow probe: a single batched residual query (the
        engine's existing compiled program — no new programs, no host
        hop beyond the result fetch every query already pays).  Updates
        the windowed ``fleet.drift.level`` gauge and the sticky trip
        state."""
        X = self._probe_X[tenant] if X is None else np.atleast_2d(
            np.asarray(X, np.float32))
        lt = self.router.load(tenant)
        mean_abs = float(np.mean(np.abs(np.asarray(lt.engine.residual(X)))))
        level = mean_abs / self._baseline[tenant]
        self._levels[tenant].append(level)
        windowed = float(np.mean(self._levels[tenant]))
        self._registry.counter("fleet.drift.probes", tenant=tenant).inc()
        self._registry.histogram("fleet.drift.residual",
                                 tenant=tenant).observe(mean_abs)
        self._registry.gauge("fleet.drift.level", tenant=tenant).set(
            round(windowed, 6))
        if windowed > self.slo.max_residual_drift \
                and not self._tripped[tenant]:
            self._tripped[tenant] = True
            self._registry.counter("fleet.drift.trips", tenant=tenant).inc()
            log_event("closedloop", f"DRIFT tripped: tenant={tenant} "
                      f"windowed residual {windowed:.2f}x baseline "
                      f"(threshold {self.slo.max_residual_drift:g}x)",
                      level="warning", verbose=self.verbose, event="drift",
                      tenant=str(tenant), drift_level=windowed,
                      threshold=self.slo.max_residual_drift)
        return windowed

    def drift(self, tenant: str) -> Optional[float]:
        """The tenant's current windowed drift level (None before any
        probe — no traffic, no verdict)."""
        levels = self._levels.get(tenant)
        return float(np.mean(levels)) if levels else None

    def tripped(self) -> tuple:
        """Tenants whose drift objective is in sticky breach (cleared by
        :meth:`reset` after a successful swap)."""
        return tuple(t for t, hit in self._tripped.items() if hit)

    def evaluate(self) -> dict:
        """The :class:`SLOSet` verdict over the monitor's registry — the
        ``residual_drift`` objective reads the gauges this monitor
        writes."""
        return self.slo.evaluate(self._registry)

    def reset(self, tenant: str, rebaseline: bool = True) -> None:
        """Clear the tenant's window + trip state after a swap; with
        ``rebaseline`` the NEW engine's probe residual becomes the new
        baseline (the swapped artifact defines fresh health)."""
        self._levels[tenant].clear()
        self._tripped[tenant] = False
        self._registry.gauge("fleet.drift.level", tenant=tenant).set(1.0)
        if rebaseline:
            self.attach(tenant, self._probe_X[tenant])

    # ------------------------------------------------------------------ #
    def _perturb_served_params(self, tenant: str, scale: float) -> None:
        """Apply the chaos ``drift_inject`` fault: deterministically
        scale the tenant's SERVED params in place.  The engine reads
        ``surrogate.params`` at call time, so the very next query (and
        probe) sees the drifted model — no reload, exactly like silent
        numeric rot on a live replica."""
        import jax.numpy as jnp
        lt = self.router.load(tenant)
        lt.surrogate.params = jax.tree_util.tree_map(
            lambda a: a * (1.0 + scale), lt.surrogate.params)


class RetrainController:
    """Drive the drift → retrain → hot-swap cycle (module docstring).

    Args:
      router / monitor: the serving fleet and its drift monitor.
      build_factory: ``build_factory(init_params) -> SurrogateFactory``
        — rebuilds the θ-neighborhood family, warm-started from the
        per-member param list the controller harvests from the LIVE
        tenants (``None`` entries fall back to fresh PRNG init).  The
        caller owns the problem definition (f_model, domain, bcs,
        thetas); the controller owns when and from where it retrains.
      members: ``{member_index: tenant}`` — the
        :meth:`~tensordiffeq_tpu.fleet.FleetRouter.register_family`
        return value; keys are ORIGINAL member indices, exactly as the
        family manifest records them.
      retrain_iters / chunk: total retrain epochs and the chunk size
        between supervisor boundaries (the kill/relaunch granularity).
      resample_every: drift-weighted collocation cadence (the
        FamilyResampler's residual-importance redraw).  ``None`` (the
        default) resamples once per chunk; ``0`` disables.
      retry: :class:`~tensordiffeq_tpu.resilience.RetryPolicy` for
        relaunch backoff between trainer-death generations (default:
        3 attempts, seeded jitter).
      gate_ratio: canary gate as a multiple of the tenant's ATTACH-TIME
        baseline residual — the recorded healthy state, not the drifted
        one (1.5 = "the retrained member must land within 1.5x of the
        residual the tenant shipped with").
      export_kw: forwarded to :meth:`~tensordiffeq_tpu.factory.
        SurrogateFactory.export_family` (bucket ladder, kinds, ...).
      workdir: where v2 family batches land (one subdirectory per
        cycle); default: a temp directory.
      sleep / clock: injectable for tests.
    """

    def __init__(self, router, monitor: DriftMonitor,
                 build_factory: Callable, members: dict, *,
                 retrain_iters: int = 200, chunk: int = 50,
                 resample_every: Optional[int] = None,
                 resample_kw: Optional[dict] = None,
                 retry: Optional[RetryPolicy] = None,
                 gate_ratio: float = 1.5,
                 export_kw: Optional[dict] = None,
                 workdir: Optional[str] = None,
                 registry=None, clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 verbose: bool = False):
        if retrain_iters < 1:
            raise ValueError(
                f"retrain_iters must be >= 1, got {retrain_iters}")
        self.router = router
        self.monitor = monitor
        self.build_factory = build_factory
        self.members = {int(m): str(t) for m, t in members.items()}
        self.retrain_iters = int(retrain_iters)
        self.chunk = max(1, int(chunk))
        self.resample_every = (self.chunk if resample_every is None
                               else int(resample_every))
        self.resample_kw = dict(resample_kw or {})
        self.retry = retry if retry is not None else RetryPolicy()
        self.gate_ratio = float(gate_ratio)
        self.export_kw = dict(export_kw or {})
        self.workdir = workdir
        self._registry = (registry if registry is not None
                          else default_registry())
        self._clock = clock
        self._sleep = sleep
        self.verbose = bool(verbose)
        self._cycles = 0

    # ------------------------------------------------------------------ #
    def live_params(self) -> list:
        """The warm-start harvest: member-index-ordered list of the LIVE
        tenants' served params (``None`` where a member has no live
        tenant — that member re-initializes from PRNG)."""
        out = []
        for m in sorted(self.members):
            lt = self.router._loaded.get(self.members[m])
            out.append(None if lt is None else lt.surrogate.params)
        return out

    def run_cycle(self, force: bool = False) -> dict:
        """One full closed-loop pass: check the trip wire, retrain the
        neighborhood under the supervisor loop, export the v2 batch,
        canary + hot-swap every member.  Returns the cycle summary
        (``{"triggered": False}`` when nothing tripped and ``force`` is
        off — the idle poll costs one dict)."""
        tripped = self.monitor.tripped()
        if not tripped and not force:
            return {"triggered": False}
        self._cycles += 1
        summary: dict = {"triggered": True, "tripped": list(tripped),
                         "cycle": self._cycles}
        tr = active_tracer()  # one probe on the untraced path
        if tr is None:
            factory = self._retrain(summary)
            v2 = self._export(factory, summary)
            self._swap_all(factory, v2, summary)
            return summary
        with tr.span("closedloop.cycle", cycle=self._cycles,
                     tripped=len(tripped)):
            factory = self._retrain(summary)
            v2 = self._export(factory, summary)
            self._swap_all(factory, v2, summary)
            return summary

    # ------------------------------------------------------------------ #
    def _retrain(self, summary: dict):
        """The supervisor loop: fit the family in chunks; a trainer
        death relaunches a new generation with RetryPolicy backoff,
        resuming from the family's surviving state (the in-process
        analogue of :class:`~tensordiffeq_tpu.resilience.
        ClusterSupervisor`'s generation relaunch)."""
        t0 = self._clock()
        factory = self.build_factory(self.live_params())
        generation, done, kills = 0, 0, 0
        while done < self.retrain_iters:
            generation += 1
            self._registry.counter("fleet.swap.generations").inc()
            log_event("closedloop",
                      f"RETRAIN generation {generation} launched: "
                      f"{factory.n_members} member(s), epochs "
                      f"{done}->{self.retrain_iters}"
                      + (" (relaunch after trainer death)"
                         if generation > 1 else ""),
                      verbose=self.verbose, event="retrain",
                      generation=generation, members=factory.n_members,
                      start_epoch=done, target_epochs=self.retrain_iters,
                      relaunch=generation > 1)
            tr = active_tracer()
            gen_span = (None if tr is None else tr.open_span(
                "closedloop.retrain", generation=generation,
                start_epoch=done))
            try:
                # the retrain job inherits the cycle's trace: anything
                # this generation spawns (a cluster-backed factory, an
                # export subprocess) reads TDQ_TRACE_CONTEXT and its
                # spans join the incident timeline
                with propagate_trace(gen_span):
                    while done < self.retrain_iters:
                        n = min(self.chunk, self.retrain_iters - done)
                        factory.fit(tf_iter=n, chunk=n,
                                    resample_every=self.resample_every,
                                    **self.resample_kw)
                        done += n
                        chaos = active_chaos()
                        if chaos is not None and done < self.retrain_iters:
                            chaos.on_retrain_boundary(generation, done)
                if gen_span is not None:
                    tr.close_span(gen_span.set_attrs(end_epoch=done))
            except ChaosFault as e:
                if gen_span is not None:
                    tr.close_span(gen_span, error=e)
                kills += 1
                if kills >= self.retry.max_attempts:
                    raise
                delay = self.retry.delay_s(kills)
                log_event("closedloop",
                          f"retrain generation {generation} died at epoch "
                          f"{done} ({e}); relaunching after {delay:.2f}s "
                          f"backoff (attempt {kills + 1}/"
                          f"{self.retry.max_attempts})", level="warning",
                          verbose=self.verbose, event="retrain_death",
                          generation=generation, epoch=done,
                          backoff_s=delay,
                          error=f"{type(e).__name__}: {e}")
                self._sleep(delay)
        wall = self._clock() - t0
        self._registry.histogram("fleet.swap.retrain_wall_s").observe(wall)
        summary.update(generations=generation, trainer_kills=kills,
                       retrain_epochs=done, retrain_wall_s=wall)
        return factory

    def _export(self, factory, summary: dict) -> str:
        """Export the v2 family batch and run the ``swap_corrupt_member``
        chaos hook over each member artifact (post-promote, like the
        torn-checkpoint fault — the corruption the checksum must
        catch)."""
        if self.workdir is None:
            import tempfile
            self.workdir = tempfile.mkdtemp(prefix="tdq_closedloop_")
        v2 = os.path.join(self.workdir, f"v{self._cycles + 1}")
        manifest = factory.export_family(v2, **self.export_kw)
        chaos = active_chaos()
        if chaos is not None:
            for m, rel in manifest["members"].items():
                chaos.on_member_artifact(int(m), os.path.join(v2, rel))
        summary.update(v2_dir=v2,
                       exported=sorted(int(m) for m in manifest["members"]),
                       frozen=sorted(int(m) for m in manifest["frozen"]))
        return v2

    def _swap_all(self, factory, v2: str, summary: dict) -> None:
        import json as _json

        from ..factory import FAMILY_MANIFEST
        with open(os.path.join(v2, FAMILY_MANIFEST)) as fh:
            manifest = _json.load(fh)
        swapped, rolled_back = [], []
        for m in sorted(self.members):
            tenant = self.members[m]
            rel = manifest["members"].get(str(m))
            if rel is None:
                # frozen mid-family: the manifest excluded it, so the
                # tenant's old engine keeps serving — narrated as a
                # rollback (that is what the route does)
                self._registry.counter("fleet.swap.rollbacks",
                                       tenant=tenant).inc()
                log_event("closedloop",
                          f"ROLLBACK: tenant={tenant} kept its old engine "
                          f"(member {m} frozen mid-family, excluded per "
                          "the manifest)", level="warning",
                          verbose=self.verbose, event="rollback",
                          tenant=tenant, member=int(m),
                          reason="member_frozen")
                rolled_back.append({"tenant": tenant, "member": int(m),
                                    "reason": "member_frozen"})
                continue
            verdict = self.router.hot_swap(
                tenant, os.path.join(v2, rel),
                f_model=factory.member_f_model(m),
                probe_X=self.monitor.probe_set(tenant),
                gate=self.monitor.baseline(tenant) * self.gate_ratio)
            verdict["member"] = int(m)
            if verdict["swapped"]:
                self.monitor.reset(tenant)
                swapped.append(verdict)
            else:
                rolled_back.append(verdict)
        summary.update(swapped=swapped, rolled_back=rolled_back)
