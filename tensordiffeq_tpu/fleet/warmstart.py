"""AOT warm start: kill the fresh-replica cold-start tax.

A fresh serving replica otherwise pays a jit storm before its first
answer: every (query kind, bucket) rung of the engine's pad-to-bucket
ladder traces + XLA-compiles on first touch, which on CPU costs hundreds
of milliseconds per program and through a TPU tunnel costs minutes.  The
fleet answer is to move ALL of that to ``FleetRouter.load()`` time:

* **artifact side** — :func:`export_fleet_artifact` embeds a warm-start
  block in the surrogate artifact: the ladder spec (min/max bucket + the
  query kinds to prewarm) plus one serialized compiled program per
  (kind, bucket) rung via ``jax.export`` where the backend supports it.
  The blobs ride the checkpoint payload (checksummed, crash-safe — see
  ``save_checkpoint(extra_files=)``), and because an exported residual
  program embeds the residual computation, an AOT artifact serves
  residual queries with **no** ``f_model`` re-attached at all.
* **replica side** — :func:`warm_start` installs the deserialized
  programs into the engine (:meth:`InferenceEngine.install_aot`) and
  drives one dummy query through every ladder rung, so every first-touch
  — AOT materialization or jit compile — happens during load.  The first
  REAL query compiles zero programs (assertable via the engine's
  per-bucket compile counters, which is exactly how ``bench.py --fleet``
  proves it).

Fallback ladder, best to worst, degrading — never failing — the load:
AOT program (backend matches, blob deserializes) → persistent-compile-
cache-served jit compile (``utils.enable_compilation_cache`` — which
keeps the PR-5 default of OFF on the CPU backend unless explicitly
opted in) → plain jit compile at load time.  Every rung lands in one of
the three; a corrupt blob (chaos ``warmstart_fail_n``, or a real torn
file caught by the artifact checksum) costs that rung its AOT entry,
nothing more.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

import jax
import numpy as np

from ..resilience.chaos import active_chaos
from ..telemetry import default_registry, log_event
from ..utils import enable_compilation_cache

#: artifact-relative directory the serialized programs live in
AOT_SUBDIR = "aot"
#: version of the warm-start meta block (independent of the artifact
#: schema version: the block is optional and self-describing)
WARMSTART_FORMAT = 1

DEFAULT_KINDS = ("u", "residual")


def _blob_relpath(spec: str, bucket: int) -> str:
    return os.path.join(AOT_SUBDIR,
                        f"{spec.replace(':', '-')}_{int(bucket)}.bin")


def _params_shapes(params):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        params)


def export_fleet_artifact(surrogate, path: str, *, min_bucket: int = 256,
                          max_bucket: int = 4096,
                          kinds: Sequence[str] = DEFAULT_KINDS,
                          aot: bool = True) -> dict:
    """Save ``surrogate`` under ``path`` with a warm-start block: the
    ladder spec, and (with ``aot=True``) one ``jax.export``-serialized
    compiled program per (kind, bucket) rung of the ladder.

    ``kinds`` are engine query-kind specs (``"u"``, ``"residual"``,
    ``"d:<var>[:<order>[:<component>]]"``).  A kind the surrogate cannot
    evaluate (``"residual"`` with no ``f_model``) raises — exporting a
    warm-start promise the replica cannot keep would be worse.  A rung
    whose program fails to export is skipped with a logged warning (the
    replica jit-compiles that rung at load time instead); the export
    never fails the save over it.

    Returns the warm-start meta block that was embedded."""
    # a throwaway engine supplies kind parsing + the exact per-bucket
    # program factories the live replica will run — exporting anything
    # else would break the fleet's bit-identity contract
    engine = surrogate.engine(min_bucket=min_bucket, max_bucket=max_bucket)
    specs = [engine.spec_for(engine.kind_key(k)) for k in kinds]
    if "residual" in specs and surrogate.point_residual is None:
        raise ValueError(
            "cannot export a residual warm start: this surrogate has no "
            "f_model attached (drop 'residual' from kinds=, or export "
            "from a compiled solver)")

    block = {"format": WARMSTART_FORMAT, "min_bucket": int(min_bucket),
             "max_bucket": int(max_bucket), "kinds": specs,
             "backend": jax.default_backend(), "aot": {}}
    files: dict = {}
    if aot:
        from jax import export as jax_export
        p_shapes = _params_shapes(surrogate.params)
        for spec in specs:
            fn = engine.make_batched(spec)()
            per_kind: dict = {}
            for bucket in engine.bucket_sizes:
                x_shape = jax.ShapeDtypeStruct(
                    (bucket, surrogate.ndim), np.float32)
                try:
                    exp = jax_export.export(jax.jit(fn))(p_shapes, x_shape)
                    blob = exp.serialize()
                except Exception as e:
                    log_event("warmstart",
                              f"AOT export failed for kind={spec} "
                              f"bucket={bucket} ({type(e).__name__}: {e}); "
                              "replica will jit this rung at load",
                              level="warning", verbose=False, kind_label=spec,
                              bucket=bucket,
                              error=f"{type(e).__name__}: {e}")
                    continue
                rel = _blob_relpath(spec, bucket)
                files[rel] = blob
                per_kind[str(bucket)] = rel
            if per_kind:
                block["aot"][spec] = per_kind
    surrogate.save(path, extra_meta={"warmstart": block},
                   extra_files=files)
    log_event("warmstart",
              f"exported fleet artifact {path}: {len(files)} AOT "
              f"program(s) over kinds={specs}, "
              f"buckets={list(engine.bucket_sizes)}", verbose=False,
              path=str(path), programs=len(files), kinds=specs)
    return block


def warm_start(engine, *, kinds: Optional[Sequence[str]] = None,
               tenant: Optional[str] = None, registry=None,
               max_drive_bucket: Optional[int] = None) -> dict:
    """Prewarm ``engine`` so its first real query compiles nothing.

    Reads the warm-start block from the engine's surrogate artifact meta
    (when the surrogate was :meth:`~tensordiffeq_tpu.serving.Surrogate.load`-ed
    from an artifact that carries one): installs every AOT program whose
    backend matches, then drives one dummy query through every ladder
    rung so each first-touch happens NOW.  Without a block (a pre-fleet
    v1 artifact, or an ``aot=False`` export) the same dummy-drive runs
    over ``kinds`` (default: ``u``, plus ``residual`` when evaluable)
    through the jit path — after wiring the persistent compile cache
    (:func:`~tensordiffeq_tpu.utils.enable_compilation_cache`, which
    keeps the CPU-off default), so on TPU repeated replica starts hit
    the disk cache.

    Never raises for a degradable reason: a corrupt blob, a backend
    mismatch, or a rung that fails to compile costs that rung its best
    tier, and the load continues.  Returns
    ``{"aot": n, "jit": n, "failed": n, "skipped": [...], "wall_s": s}``.
    """
    registry = registry if registry is not None else default_registry()
    sur = engine.surrogate
    block = (sur.artifact_meta or {}).get("warmstart")
    t0 = time.monotonic()

    # fallback tier 2: the persistent compile cache (no-op on CPU by
    # default — the PR-5 correctness stance — but primes TPU replicas)
    cache_dir = enable_compilation_cache()

    # the artifact block's own kinds win when present: the artifact knows
    # what it carries (an explicit kinds= that DROPPED a block kind would
    # skip installing AOT programs a no-f_model replica depends on);
    # kinds= is the fallback for block-less (v1 / aot=False) artifacts
    if block:
        kinds = block["kinds"]
    elif kinds is None:
        kinds = list(DEFAULT_KINDS)

    # drive ladder cap: the warm promise is the ARTIFACT's ladder, not
    # the policy engine's — a default-policy engine tops out at 2^20 and
    # driving a million-point residual dummy query (13 rungs x kinds of
    # compiles) would turn load() into the very storm warm start exists
    # to kill.  Without a block, cap at the rung the tenant's coalescing
    # policy actually produces (max_drive_bucket = the batcher's
    # max_batch); rungs past the cap still compile lazily on first real
    # demand, which is the pre-fleet behavior for shapes that rare.
    cap = engine.bucket_sizes[-1]
    if block:
        cap = min(cap, int(block["max_bucket"]))
    elif max_drive_bucket is not None:
        cap = min(cap, engine.bucket_for(int(max_drive_bucket)))
    aot_index = (block or {}).get("aot", {})
    backend_ok = (block or {}).get("backend") == jax.default_backend()
    if block and block.get("aot") and not backend_ok:
        log_event("warmstart",
                  f"AOT programs were exported for backend "
                  f"{(block or {}).get('backend')!r} but this replica "
                  f"runs {jax.default_backend()!r}; jit-prewarming "
                  "instead", level="warning", verbose=False,
                  tenant=tenant)

    n_aot = n_jit = n_failed = 0
    skipped: list = []
    for spec in kinds:
        key = engine.kind_key(spec)
        spec = engine.spec_for(key)
        blobs = aot_index.get(spec, {}) if backend_ok else {}
        # install every rung's AOT program BEFORE the first drive: the
        # residual kind with no f_model is only evaluable through them
        installed = set()
        for bucket in engine.bucket_sizes:
            if bucket > cap:
                continue
            rel = blobs.get(str(bucket))
            if rel is None or sur.artifact_dir is None:
                continue
            try:
                chaos = active_chaos()
                if chaos is not None:
                    chaos.on_warmstart(spec, bucket)
                with open(os.path.join(sur.artifact_dir, rel), "rb") as fh:
                    blob = fh.read()
                from jax import export as jax_export
                exp = jax_export.deserialize(bytearray(blob))
                engine.install_aot(
                    spec, bucket,
                    lambda params, X, _e=exp: _e.call(params, X))
                installed.add(bucket)
            except Exception as e:  # ChaosFault included — degrade, don't die
                n_failed += 1
                registry.counter("fleet.warmstart.aot_failed",
                                 **({"tenant": tenant} if tenant else {})
                                 ).inc()
                log_event("warmstart",
                          f"AOT program kind={spec} bucket={bucket} "
                          f"unusable ({type(e).__name__}: {e}); rung "
                          "falls back to jit", level="warning",
                          verbose=False, tenant=tenant, kind_label=spec,
                          bucket=bucket, error=f"{type(e).__name__}: {e}")
        if spec == "residual" and sur.point_residual is None \
                and not installed:
            skipped.append(spec)  # nothing can evaluate it on this replica
            continue
        op = engine.op_for(spec)
        dead = set(engine.quarantine_snapshot())
        for bucket in engine.bucket_sizes:
            if bucket > cap:
                continue
            if (spec, bucket) in dead:
                continue  # eviction memory: never resurrect a dead rung
            try:
                op(np.zeros((bucket, sur.ndim), np.float32))
            except Exception as e:
                n_failed += 1
                log_event("warmstart",
                          f"prewarm drive failed for kind={spec} "
                          f"bucket={bucket} ({type(e).__name__}: {e})",
                          level="warning", verbose=False, tenant=tenant,
                          kind_label=spec, bucket=bucket,
                          error=f"{type(e).__name__}: {e}")
                continue
            if bucket in installed and engine.has_aot(spec, bucket):
                n_aot += 1
            else:
                if bucket in installed:
                    # installed but dropped at first use (the engine fell
                    # back to jit mid-drive): the AOT tier did NOT pay
                    n_failed += 1
                n_jit += 1
    wall = time.monotonic() - t0
    labels = {"tenant": tenant} if tenant else {}
    registry.counter("fleet.warmstart.programs", mode="aot",
                     **labels).inc(n_aot)
    registry.counter("fleet.warmstart.programs", mode="jit",
                     **labels).inc(n_jit)
    registry.histogram("fleet.warmstart.wall_s", **labels).observe(wall)
    out = {"aot": n_aot, "jit": n_jit, "failed": n_failed,
           "skipped": skipped, "compile_cache_dir": cache_dir,
           "wall_s": wall}
    log_event("warmstart",
              f"warm start{f' tenant={tenant}' if tenant else ''}: "
              f"{n_aot} AOT + {n_jit} jit program(s) in {wall:.3f}s"
              + (f", {n_failed} degraded" if n_failed else ""),
              verbose=False, tenant=tenant, **{k: v for k, v in out.items()
                                               if k != "skipped"})
    return out
