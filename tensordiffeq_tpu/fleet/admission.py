"""Admission control: decide a request's fate BEFORE it queues.

A fleet under overload has exactly two choices: shed load at the front
door with a structured, retryable rejection, or let queues grow until
every tenant's latency collapses together.  This module is the front
door.  Checks run in a fixed order — per-tenant queue bound, fleet-wide
watermarks, then the token-bucket rate limit LAST (consuming a token is
a side effect: a request shed for any other reason must not also burn
rate budget) — and a request that
fails any of them raises :class:`AdmissionRejected` carrying the tenant,
a machine-readable reason, and a ``retry_after_s`` backpressure hint
(the same contract shape as the circuit breaker's
:class:`~tensordiffeq_tpu.resilience.CircuitOpenError`).

Priority is the shedding ORDER, enforced at admission rather than by
re-ordering queues: under fleet-wide pressure low-priority (0) traffic is
shed first at ``shed_watermark``, normal traffic (1) at saturation, and
critical traffic (2) rides the reserved headroom above the watermark —
so by the time the fleet is full, what remains queued is already sorted
by priority without touching the batcher's FIFO coalescing.  Per-tenant
limits (rate, queue bound) apply to every priority: criticality does not
exempt a tenant from its own contract.

Everything is deterministic and clock-injectable; rejections land in the
shared registry (``fleet.admission.rejected{tenant=,reason=}``) and the
run log (``admission`` events), so :func:`tensordiffeq_tpu.telemetry.report`
can narrate an overload window after the fact.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..telemetry import default_registry, log_event
from ..telemetry.tracing import active_tracer, attach_trace

#: priority levels: 0 = batch/background (shed first), 1 = interactive
#: (default), 2 = critical (rides the reserved headroom)
PRIORITIES = (0, 1, 2)


class AdmissionRejected(RuntimeError):
    """Structured front-door rejection.  ``reason`` is machine-readable:
    ``rate_limit`` (tenant over its QPS budget), ``tenant_queue_full``
    (tenant's own queue bound), ``load_shed`` (fleet past the shed
    watermark; priority 0 traffic), or ``fleet_saturated`` (fleet at
    capacity; priority <= 1 traffic).  ``retry_after_s`` is the
    backpressure hint (0 when retrying immediately might succeed, e.g.
    after other tenants drain).  ``trace_id`` is stamped when a
    :class:`~tensordiffeq_tpu.telemetry.Tracer` is active — the id
    resolves the rejection's span in the run log."""

    trace_id = None

    def __init__(self, tenant: str, reason: str,
                 retry_after_s: float = 0.0, detail: str = ""):
        self.tenant = str(tenant)
        self.reason = str(reason)
        self.retry_after_s = max(0.0, float(retry_after_s))
        msg = (f"admission rejected for tenant {tenant!r}: {reason}"
               + (f" ({detail})" if detail else ""))
        if self.retry_after_s > 0:
            msg += f"; retry in {self.retry_after_s:.3f}s"
        super().__init__(msg)


class _TokenBucket:
    """Per-tenant request-rate limiter: ``rate`` tokens/s refill up to
    ``burst``; one admitted request costs one token."""

    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.last = now

    def take(self, now: float) -> Optional[float]:
        """Consume one token; returns None on success, or the seconds
        until one becomes available."""
        self.tokens = min(self.burst,
                          self.tokens + (now - self.last) * self.rate)
        self.last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return None
        return (1.0 - self.tokens) / self.rate if self.rate > 0 else 60.0


class AdmissionController:
    """Front-door policy for a :class:`~tensordiffeq_tpu.fleet.FleetRouter`.

    Args:
      max_pending_points: fleet-wide pending-point capacity.  At or past
        it, only priority-2 traffic is admitted (``fleet_saturated``).
      shed_watermark: fraction of ``max_pending_points`` past which
        priority-0 traffic is shed (``load_shed``) — the early-warning
        band that keeps interactive traffic's queue short.
      clock: time source (injectable for tests).
      registry: metrics destination (default: the shared process
        registry; the router passes its own).

    Per-tenant knobs arrive via :meth:`configure` (the router forwards
    them from each tenant's :class:`~tensordiffeq_tpu.fleet.TenantPolicy`):
    ``rate_qps``/``burst`` (token bucket; None = unlimited),
    ``max_queue_points`` (tenant queue bound; None = unbounded), and the
    tenant's default ``priority``.
    """

    def __init__(self, max_pending_points: int = 262_144,
                 shed_watermark: float = 0.75,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        if not 0.0 < shed_watermark <= 1.0:
            raise ValueError(f"shed_watermark must be in (0, 1], "
                             f"got {shed_watermark}")
        self.max_pending_points = int(max_pending_points)
        self.shed_watermark = float(shed_watermark)
        self._clock = clock
        self._buckets: dict = {}
        self._limits: dict = {}
        self._nominal: Optional[tuple] = None  # set lazily by degrade()
        self._metrics = (registry if registry is not None
                         else default_registry())

    # ------------------------------------------------------------------ #
    def degrade(self, factor: float = 0.5) -> None:
        """Tighten the fleet-wide watermarks to ``factor`` of their
        NOMINAL values (graceful degradation below replica quorum: with
        half the group gone, half the queue capacity keeps per-request
        latency honest instead of letting survivors drown).  Relative to
        the nominal configuration, so repeated calls are idempotent and
        re-degrading at a different factor never compounds."""
        if not 0.0 < float(factor) <= 1.0:
            raise ValueError(f"factor must be in (0, 1], got {factor}")
        if self._nominal is None:
            self._nominal = (self.max_pending_points, self.shed_watermark)
        nom_cap, nom_shed = self._nominal
        self.max_pending_points = max(1, int(nom_cap * float(factor)))
        self.shed_watermark = nom_shed * float(factor)
        self._metrics.gauge("fleet.admission.degraded").set(1)
        log_event("admission", f"degraded watermarks to {factor:.0%} of "
                  f"nominal (capacity {self.max_pending_points}, shed at "
                  f"{self.shed_watermark:.0%})", level="warning",
                  verbose=False, factor=float(factor),
                  max_pending_points=self.max_pending_points)

    def restore(self) -> None:
        """Undo :meth:`degrade`: watermarks back to nominal (no-op when
        never degraded)."""
        if self._nominal is None:
            return
        self.max_pending_points, self.shed_watermark = self._nominal
        self._nominal = None
        self._metrics.gauge("fleet.admission.degraded").set(0)
        log_event("admission", "restored nominal watermarks (capacity "
                  f"{self.max_pending_points}, shed at "
                  f"{self.shed_watermark:.0%})", verbose=False,
                  max_pending_points=self.max_pending_points)

    # ------------------------------------------------------------------ #
    def configure(self, tenant: str, *, rate_qps: Optional[float] = None,
                  burst: Optional[float] = None,
                  max_queue_points: Optional[int] = None,
                  priority: int = 1) -> None:
        """Install (or replace) one tenant's limits."""
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority}")
        if rate_qps is not None and rate_qps <= 0:
            raise ValueError(f"rate_qps must be > 0 (got {rate_qps}); "
                             "use None for unlimited")
        if burst is not None and burst < 1.0:
            raise ValueError(
                f"burst must be >= 1 (got {burst}): a bucket that can "
                "never hold one whole token admits nothing, forever, "
                "while promising a retry_after_s that cannot come true")
        self._limits[tenant] = {
            "rate_qps": None if rate_qps is None else float(rate_qps),
            "max_queue_points": (None if max_queue_points is None
                                 else int(max_queue_points)),
            "priority": int(priority),
        }
        if rate_qps is not None:
            self._buckets[tenant] = _TokenBucket(
                rate_qps, burst if burst is not None
                else max(1.0, float(rate_qps)), self._clock())
        else:
            self._buckets.pop(tenant, None)

    def priority_for(self, tenant: str) -> int:
        return self._limits.get(tenant, {}).get("priority", 1)

    # ------------------------------------------------------------------ #
    def _reject(self, tenant: str, reason: str, retry_after_s: float,
                detail: str = ""):
        self._metrics.counter("fleet.admission.rejected", tenant=tenant,
                              reason=reason).inc()
        log_event("admission",
                  f"rejected tenant={tenant} reason={reason}"
                  + (f" ({detail})" if detail else ""),
                  level="warning", verbose=False, tenant=tenant,
                  reason=reason, retry_after_s=retry_after_s)
        raise attach_trace(
            AdmissionRejected(tenant, reason, retry_after_s, detail))

    def admit(self, tenant: str, n_points: int,
              priority: Optional[int] = None, *,
              tenant_pending: int = 0, fleet_pending: int = 0) -> None:
        """Gate one request of ``n_points`` rows.  Raises
        :class:`AdmissionRejected` or returns None (admitted); with a
        tracer active the decision is a ``fleet.admission`` span
        (``status=error`` on a shed, carrying the reason).  The
        router passes the live queue depths; standalone callers may
        pass their own."""
        tr = active_tracer()  # one probe when tracing is off
        if tr is None:
            return self._admit(tenant, n_points, priority,
                               tenant_pending=tenant_pending,
                               fleet_pending=fleet_pending)
        with tr.span("fleet.admission", tenant=str(tenant),
                     n=int(n_points)):
            return self._admit(tenant, n_points, priority,
                               tenant_pending=tenant_pending,
                               fleet_pending=fleet_pending)

    def _admit(self, tenant: str, n_points: int,
               priority: Optional[int] = None, *,
               tenant_pending: int = 0, fleet_pending: int = 0) -> None:
        if priority is None:
            priority = self.priority_for(tenant)
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, "
                             f"got {priority}")
        limits = self._limits.get(tenant, {})

        # 1. tenant queue bound
        mqp = limits.get("max_queue_points")
        if mqp is not None and tenant_pending + int(n_points) > mqp:
            self._reject(tenant, "tenant_queue_full", 0.0,
                         f"{tenant_pending} pending + {n_points} > {mqp}")

        # 2. fleet-wide watermarks: the priority-ordered shed
        if fleet_pending >= self.max_pending_points and priority < 2:
            self._reject(tenant, "fleet_saturated", 0.0,
                         f"{fleet_pending} >= {self.max_pending_points} "
                         "fleet pending points")
        if fleet_pending >= self.shed_watermark * self.max_pending_points \
                and priority < 1:
            self._reject(tenant, "load_shed", 0.0,
                         f"{fleet_pending} past the "
                         f"{self.shed_watermark:.0%} shed watermark")

        # 3. tenant rate limit LAST — consuming the token is a side
        #    effect, so a request shed for any other reason must not
        #    also burn rate budget (overload retries against a full
        #    queue would otherwise double-penalize the tenant).  It
        #    applies to every priority: criticality does not exempt a
        #    tenant from its own contract.
        bucket = self._buckets.get(tenant)
        if bucket is not None:
            wait = bucket.take(self._clock())
            if wait is not None:
                self._reject(tenant, "rate_limit", wait,
                             f"{limits.get('rate_qps')} req/s budget")

        self._metrics.counter("fleet.admission.admitted",
                              tenant=tenant).inc()
