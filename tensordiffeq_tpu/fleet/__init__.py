"""Multi-tenant serving fleet: LRU artifact cache, admission control,
AOT warm start.

``serving/`` deploys ONE surrogate; this package deploys MANY — the
"millions of users" direction of the roadmap made concrete.  A
:class:`FleetRouter` hot-loads surrogate artifacts behind a bounded LRU
cache (evicted engines drop their jit ladders; reloads go through the
checksum-validated checkpoint restore), gives every tenant its own
coalescing batchers with their own retry/breaker/deadline policy
(:class:`TenantPolicy`), sheds overload at the front door with a
structured :class:`AdmissionRejected` (:class:`AdmissionController`:
token-bucket rate limits, queue bounds, priority-ordered load shedding),
and kills the fresh-replica cold-start tax with an AOT warm start
(:func:`export_fleet_artifact` / :func:`warm_start`: ``jax.export``-
serialized per-rung programs riding the artifact, persistent-compile-
cache prewarm as the fallback).  Autoscaling signals — queue-depth
gauges, latency histograms, cache hit/miss/eviction counters — publish
through the shared telemetry registry
(:meth:`FleetRouter.autoscale_signals` distils them).

The serving plane replicates in :mod:`~tensordiffeq_tpu.fleet.replica`:
a :class:`ReplicaGroup` runs N router processes (each the full tenant
set, warm-started from the shared artifact directory) under a
serving-mode :class:`~tensordiffeq_tpu.resilience.ClusterSupervisor`
that respawns a lost replica in place, and a :class:`FrontRouter`
rendezvous-hashes tenants onto replicas with per-replica circuit
breakers, retrying failover, optional hedged requests, and
below-quorum graceful degradation — chaos-drilled so one replica's
death loses zero requests.

The loop closes in :mod:`~tensordiffeq_tpu.fleet.closedloop`: a
:class:`DriftMonitor` shadow-samples live traffic through the residual
kind and trips the ``residual_drift`` SLO, a :class:`RetrainController`
retrains the drifting θ neighborhood (factory warm-started from the live
members' served params) under a supervisor loop with retry backoff, and
:meth:`FleetRouter.hot_swap` flips each tenant to its canary-validated
v2 member with zero downtime — or proves the rollback bit-identical.

Typical flow::

    # train side, once per tenant:
    from tensordiffeq_tpu import fleet
    fleet.export_fleet_artifact(solver.export_surrogate(), "runs/ac",
                                min_bucket=64, max_bucket=4096)

    # serving replica (fresh process):
    router = fleet.FleetRouter(max_loaded=8)
    router.register("ac", "runs/ac",
                    policy=fleet.TenantPolicy(min_bucket=64,
                                              max_bucket=4096,
                                              rate_qps=500.0))
    router.load("ac")                    # warm start: zero request-time
    u = router.query("ac", X)            # compiles from here on
"""

from .admission import (PRIORITIES, AdmissionController,  # noqa: F401
                        AdmissionRejected)
from .closedloop import DriftMonitor, RetrainController  # noqa: F401
from .replica import (FrontRouter, ReplicaGroup,  # noqa: F401
                      ReplicaRequestError, ReplicaServer,
                      ReplicaUnavailable, decode_array, encode_array)
from .router import (FleetRouter, LoadedTenant,  # noqa: F401
                     TenantEvicted, TenantPolicy)
from .warmstart import (AOT_SUBDIR, DEFAULT_KINDS,  # noqa: F401
                        export_fleet_artifact, warm_start)
