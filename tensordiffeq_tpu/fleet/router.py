"""The multi-tenant fleet router: many surrogates, one front door.

One trained surrogate per PDE/config is the breadth direction PINNs-TF2
(arXiv:2311.03626) motivates on the training side; a real deployment
hosts MANY of them at once behind one process.  :class:`FleetRouter`
composes the pieces the previous PRs built into that layer:

* a **bounded LRU artifact cache** — at most ``max_loaded`` tenants hold
  live engines (each engine owns a jit ladder of compiled programs, the
  scarce resource); the least-recently-used tenant is evicted to make
  room, its pending batches flushed and its jit ladder dropped.  A
  reload goes back through the checksum-validated restore path
  (:mod:`tensordiffeq_tpu.checkpoint`), and the evicted engine's bucket
  quarantine is carried across the reload — a rung that failed to
  compile is NOT resurrected as healthy just because memory pressure
  cycled the tenant.
* **per-tenant serving policy** — each tenant's
  :class:`~tensordiffeq_tpu.serving.RequestBatcher` set (one per query
  kind) runs under its own :class:`~tensordiffeq_tpu.resilience.RetryPolicy`,
  :class:`~tensordiffeq_tpu.resilience.CircuitBreaker` and request
  deadline (:class:`TenantPolicy`): one tenant's dying backend opens one
  tenant's breaker.
* **admission before queue** — every submit passes the
  :class:`~tensordiffeq_tpu.fleet.AdmissionController` BEFORE anything
  is enqueued (or even loaded), so overload sheds with a structured
  :class:`~tensordiffeq_tpu.fleet.AdmissionRejected` at the front door
  instead of collapsing the queues behind it.
* **AOT warm start** — ``load()`` runs the
  :func:`~tensordiffeq_tpu.fleet.warm_start` ladder, so a freshly loaded
  tenant answers its first query without compiling anything at request
  time.
* **autoscaling signals** — per-tenant queue-depth gauges, latency
  histograms and cache hit/miss/eviction counters all land in the
  shared :func:`~tensordiffeq_tpu.telemetry.default_registry` (tenant-
  labeled via registry scopes); :meth:`autoscale_signals` distils the
  scale-up/down inputs an operator loop polls.

With no chaos active, a fleet-served query is bit-identical to the same
query against a direct :class:`~tensordiffeq_tpu.serving.InferenceEngine`
over the same artifact (``tests/test_fleet.py`` pins this).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, Optional, Sequence

import numpy as np

from ..resilience.breaker import CircuitBreaker
from ..resilience.chaos import active_chaos
from ..serving.batcher import RequestBatcher
from ..serving.surrogate import Surrogate
from ..telemetry import default_registry, log_event
from ..telemetry.slo import SLOSet
from ..telemetry.tracing import active_tracer
from .admission import AdmissionController
from .warmstart import warm_start


class TenantEvicted(RuntimeError):
    """Delivered to waiters whose coalesced batch could not execute
    (circuit breaker open) before their tenant was evicted — a
    structured immediate failure instead of a deadline spin against an
    engine that no longer exists."""

    trace_id = None

    def __init__(self, tenant: str):
        self.tenant = str(tenant)
        super().__init__(
            f"tenant {tenant!r} was evicted before this request's batch "
            "could execute (circuit open at eviction); resubmit to "
            "trigger a reload")


class TenantPolicy:
    """One tenant's serving-policy knobs (engine shape, batching,
    resilience, admission).  Pure configuration — safe to share between
    tenants that want identical policy.

    Args:
      min_bucket / max_bucket / shard: the tenant engine's pad-to-bucket
        ladder (see :class:`~tensordiffeq_tpu.serving.InferenceEngine`).
      max_batch / max_latency_s: the tenant batchers' coalescing policy.
      retry: optional :class:`~tensordiffeq_tpu.resilience.RetryPolicy`
        for this tenant's batchers (shared across its query kinds).
      breaker_failure_threshold / breaker_reset_timeout_s: when the
        threshold is not None, each *load* of this tenant gets its own
        :class:`~tensordiffeq_tpu.resilience.CircuitBreaker` (named
        ``fleet.<tenant>``) shared across its query-kind batchers.
      request_timeout_s: per-request deadline (None disables — serve
        with one).
      rate_qps / burst / max_queue_points / priority: the tenant's
        admission-control contract (see
        :class:`~tensordiffeq_tpu.fleet.AdmissionController`).
      warm_start: prewarm the engine ladder at load time (the fleet
        default).  ``False`` loads cold — first queries pay jit compiles
        at request time (what ``bench.py --fleet`` prices the warm path
        against).
      warm_kinds: query kinds to prewarm when the artifact carries no
        warm-start block (v1 artifacts); an artifact block's own kinds
        win when present.
    """

    def __init__(self, *, min_bucket: int = 256, max_bucket: int = 1 << 20,
                 shard: bool = False, max_batch: int = 4096,
                 max_latency_s: float = 0.01, retry=None,
                 breaker_failure_threshold: Optional[int] = None,
                 breaker_reset_timeout_s: float = 30.0,
                 request_timeout_s: Optional[float] = 30.0,
                 rate_qps: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_queue_points: Optional[int] = None,
                 priority: int = 1, warm_start: bool = True,
                 warm_kinds: Optional[Sequence[str]] = None):
        self.min_bucket = int(min_bucket)
        self.max_bucket = int(max_bucket)
        self.shard = bool(shard)
        self.max_batch = int(max_batch)
        self.max_latency_s = float(max_latency_s)
        self.retry = retry
        self.breaker_failure_threshold = breaker_failure_threshold
        self.breaker_reset_timeout_s = float(breaker_reset_timeout_s)
        self.request_timeout_s = request_timeout_s
        self.rate_qps = rate_qps
        self.burst = burst
        self.max_queue_points = max_queue_points
        self.priority = int(priority)
        self.warm_start = bool(warm_start)
        self.warm_kinds = None if warm_kinds is None else list(warm_kinds)


class _Registration:
    """What the router remembers about a tenant across load/evict cycles."""

    __slots__ = ("artifact", "f_model", "net", "policy", "quarantine")

    def __init__(self, artifact, f_model, net, policy):
        self.artifact = artifact
        self.f_model = f_model
        self.net = net
        self.policy = policy
        self.quarantine: list = []  # engine.quarantine_snapshot() carryover


class LoadedTenant:
    """A live tenant: surrogate + engine + per-kind batchers + breaker."""

    def __init__(self, tenant: str, surrogate: Surrogate, engine,
                 policy: TenantPolicy, registry, clock, warm: dict):
        self.tenant = tenant
        self.surrogate = surrogate
        self.engine = engine
        self.policy = policy
        self.warm = warm
        self._registry = registry
        self._clock = clock
        self.breaker = None
        if policy.breaker_failure_threshold is not None:
            self.breaker = CircuitBreaker(
                failure_threshold=policy.breaker_failure_threshold,
                reset_timeout_s=policy.breaker_reset_timeout_s,
                name=f"fleet.{tenant}", clock=clock, registry=registry)
        self._batchers: "OrderedDict[str, RequestBatcher]" = OrderedDict()

    def batcher(self, kind: str = "u") -> RequestBatcher:
        """The tenant's coalescing batcher for one query kind (created
        lazily; all kinds share the tenant's breaker + retry policy)."""
        spec = self.engine.spec_for(self.engine.kind_key(kind))
        b = self._batchers.get(spec)
        if b is None:
            b = self._batchers[spec] = RequestBatcher(
                op=self.engine.op_for(spec),
                max_batch=self.policy.max_batch,
                max_latency_s=self.policy.max_latency_s,
                retry=self.policy.retry, breaker=self.breaker,
                request_timeout_s=self.policy.request_timeout_s,
                clock=self._clock,
                registry=self._registry.scope(kind=spec))
        return b

    def pending_points(self) -> int:
        return sum(b.pending_points for b in self._batchers.values())

    def flush(self) -> None:
        """Flush every kind's pending batch (failures are delivered to
        their waiters by the batcher itself)."""
        for b in self._batchers.values():
            try:
                b.flush()
            except Exception:
                pass  # waiters already hold the failure

    def drain(self) -> None:
        """Eviction-time flush: try to execute pending batches, then
        fail-fast whatever could NOT run (an open breaker makes
        ``flush()`` a no-op that keeps the queue) — no waiter may be
        left spinning against an engine that is being dropped."""
        self.flush()
        for b in self._batchers.values():
            if b.pending_points:
                b.fail_pending(TenantEvicted(self.tenant))

    def poll(self) -> bool:
        return any([b.poll() for b in self._batchers.values()])

    def stats(self) -> dict:
        return {spec: b.stats() for spec, b in self._batchers.items()}

    def snapshot(self) -> dict:
        """One CONSISTENT observation of this tenant: per-kind batcher
        stats and the pending-point total captured from the SAME
        per-batcher snapshots (:meth:`RequestBatcher.snapshot`), so a
        flush racing the scrape can never tear the two apart."""
        snaps = {spec: b.snapshot()
                 for spec, b in tuple(self._batchers.items())}
        return {
            "kinds": {spec: s["stats"] for spec, s in snaps.items()},
            "pending_points": sum(s["pending_points"]
                                  for s in snaps.values()),
        }


class FleetRouter:
    """Route multi-tenant surrogate queries; see the module docstring.

    Args:
      max_loaded: LRU bound on concurrently live tenants (engines).
      admission: an :class:`~tensordiffeq_tpu.fleet.AdmissionController`
        (one is built with defaults when omitted; pass your own to tune
        fleet-wide capacity).
      registry: metrics destination (default: the shared process
        registry).  Per-tenant instruments are tenant-labeled scopes of
        it.
      clock: time source, injectable for tests (threads through
        batchers, breakers and the admission controller built here).
      slo: the :class:`~tensordiffeq_tpu.telemetry.SLOSet` whose verdict
        rides in :meth:`autoscale_signals` (default: the standard set),
        so an operator loop scales up on SLO burn, not just on queue
        depth.  Evaluation runs only when signals are polled — the
        default costs nothing between polls.
    """

    def __init__(self, max_loaded: int = 4,
                 admission: Optional[AdmissionController] = None,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 slo: Optional[SLOSet] = None):
        if max_loaded < 1:
            raise ValueError(f"max_loaded must be >= 1, got {max_loaded}")
        self.max_loaded = int(max_loaded)
        self._registry = (registry if registry is not None
                          else default_registry())
        self._clock = clock
        self.slo = slo if slo is not None else SLOSet.default()
        self.admission = (admission if admission is not None
                          else AdmissionController(clock=clock,
                                                   registry=self._registry))
        self._registered: dict = {}
        self._loaded: "OrderedDict[str, LoadedTenant]" = OrderedDict()
        self._hits = self._misses = self._evictions = 0
        self.collector = None  # set by serve_metrics

    # ------------------------------------------------------------------ #
    def register(self, tenant: str, artifact: str, *, f_model=None,
                 net=None, policy: Optional[TenantPolicy] = None) -> None:
        """Register a tenant: artifact path + user code (``f_model``,
        custom ``net``) + policy.  Registration is cheap — nothing loads
        until the first query (or an explicit :meth:`load`).
        Re-registering replaces the entry (a live instance is evicted
        first: the old artifact must not keep serving)."""
        if tenant in self._loaded:
            self.evict(tenant)
        self._registered[tenant] = _Registration(
            str(artifact), f_model, net, policy or TenantPolicy())
        self.admission.configure(
            tenant,
            rate_qps=self._registered[tenant].policy.rate_qps,
            burst=self._registered[tenant].policy.burst,
            max_queue_points=self._registered[tenant].policy.max_queue_points,
            priority=self._registered[tenant].policy.priority)

    def register_family(self, path: str, *,
                        policy: Optional[TenantPolicy] = None,
                        prefix: Optional[str] = None,
                        f_models: Optional[dict] = None) -> dict:
        """Register every member of a surrogate-factory artifact batch
        (:meth:`~tensordiffeq_tpu.factory.SurrogateFactory.
        export_family`): reads ``family_manifest.json`` under ``path``
        and registers each live member's v2 AOT artifact as a tenant —
        the factory's product loads directly into the fleet.  Frozen
        (diverged) members recorded in the manifest are skipped; member
        AOT artifacts serve residual queries with no ``f_model``
        re-attached (the exported program embeds the computation), but
        ``f_models`` — ``{member_index: f_model}`` with the member's θ
        already bound — re-attaches user code where the jit fallback
        path needs it.  Returns ``{member_index: tenant_name}`` keyed
        by the ORIGINAL member index (mirroring the manifest), never a
        positional sequence: with a frozen member skipped, positions
        would silently shift every later member onto the wrong
        coefficient."""
        import json as _json
        import os as _os

        from ..factory import FAMILY_MANIFEST
        with open(_os.path.join(path, FAMILY_MANIFEST)) as fh:
            manifest = _json.load(fh)
        names = {}
        for m, rel in sorted(manifest["members"].items(),
                             key=lambda kv: int(kv[0])):
            tenant = rel if prefix is None else f"{prefix}{int(m):03d}"
            self.register(
                tenant, _os.path.join(path, rel),
                f_model=(f_models or {}).get(int(m)), policy=policy)
            names[int(m)] = tenant
        return names

    def tenants(self) -> tuple:
        return tuple(self._registered)

    def loaded(self) -> tuple:
        """Live tenants, LRU-first (the leftmost is next to evict)."""
        return tuple(self._loaded)

    def _reg(self, tenant: str) -> _Registration:
        reg = self._registered.get(tenant)
        if reg is None:
            raise KeyError(
                f"tenant {tenant!r} is not registered (known: "
                f"{sorted(self._registered)})")
        return reg

    # ------------------------------------------------------------------ #
    def load(self, tenant: str) -> LoadedTenant:
        """The tenant's live instance: a cache hit refreshes its LRU slot;
        a miss evicts down to ``max_loaded - 1``, restores the artifact
        through the checksum-validated checkpoint path, re-applies the
        tenant's quarantine memory, and warm-starts the engine.  With a
        tracer active the load-or-hit is a ``fleet.load`` span."""
        tr = active_tracer()
        if tr is None:
            return self._load(tenant)
        with tr.span("fleet.load", tenant=str(tenant)) as sp:
            hits0 = self._hits
            lt = self._load(tenant)
            sp.set_attrs(cache=("hit" if self._hits > hits0 else "miss"))
            return lt

    def _load(self, tenant: str) -> LoadedTenant:
        reg = self._reg(tenant)
        chaos = active_chaos()
        if chaos is not None and chaos.on_fleet_access(
                evictable=bool(self._loaded)):
            self.evict()
        lt = self._loaded.get(tenant)
        if lt is not None:
            self._loaded.move_to_end(tenant)
            self._hits += 1
            self._registry.counter("fleet.cache.hits", tenant=tenant).inc()
            return lt
        self._misses += 1
        self._registry.counter("fleet.cache.misses", tenant=tenant).inc()
        while len(self._loaded) >= self.max_loaded:
            self.evict()
        t0 = self._clock()
        sur = Surrogate.load(reg.artifact, f_model=reg.f_model, net=reg.net)
        scope = self._registry.scope(tenant=tenant)
        engine = sur.engine(min_bucket=reg.policy.min_bucket,
                            max_bucket=reg.policy.max_bucket,
                            shard=reg.policy.shard, registry=scope)
        if reg.quarantine:
            engine.restore_quarantine(reg.quarantine)
        warm: dict = {}
        if reg.policy.warm_start:
            warm = warm_start(engine, kinds=reg.policy.warm_kinds,
                              tenant=tenant, registry=self._registry,
                              max_drive_bucket=reg.policy.max_batch)
        lt = LoadedTenant(tenant, sur, engine, reg.policy, scope,
                          self._clock, warm)
        self._loaded[tenant] = lt
        load_s = self._clock() - t0
        self._registry.histogram("fleet.load_s").observe(load_s)
        self._registry.gauge("fleet.loaded_tenants").set(len(self._loaded))
        log_event("fleet",
                  f"loaded tenant={tenant} from {reg.artifact} in "
                  f"{load_s:.3f}s"
                  + (f" (warm start: {warm.get('aot', 0)} AOT + "
                     f"{warm.get('jit', 0)} jit)" if warm else " (cold)"),
                  verbose=False, event="load", tenant=tenant,
                  load_s=load_s, warm=bool(warm))
        return lt

    def evict(self, tenant: Optional[str] = None) -> Optional[str]:
        """Drop a live tenant (default: the LRU one).  Pending batches
        are flushed first, the engine's quarantine is snapshotted into
        the registration (reload carries it), and the jit ladder goes
        with the engine.  Returns the evicted tenant (None if nothing
        was loaded)."""
        if tenant is None:
            if not self._loaded:
                return None
            tenant = next(iter(self._loaded))
        lt = self._loaded.pop(tenant, None)
        if lt is None:
            return None
        lt.drain()
        self._reg(tenant).quarantine = lt.engine.quarantine_snapshot()
        self._evictions += 1
        self._registry.counter("fleet.cache.evictions",
                               tenant=tenant).inc()
        self._registry.gauge("fleet.loaded_tenants").set(len(self._loaded))
        log_event("fleet",
                  f"evicted tenant={tenant} (LRU, {len(self._loaded)}/"
                  f"{self.max_loaded} loaded); jit ladder dropped, "
                  f"{len(self._reg(tenant).quarantine)} quarantined "
                  "rung(s) remembered", verbose=False, event="evict",
                  tenant=tenant, loaded=len(self._loaded))
        return tenant

    # ------------------------------------------------------------------ #
    def hot_swap(self, tenant: str, artifact: str, *, f_model=None,
                 net=None, probe_X=None, gate: Optional[float] = None,
                 gate_ratio: float = 1.0) -> dict:
        """Zero-downtime artifact swap with canary validation and
        bit-validated rollback (the closed loop's cutover; see
        :mod:`tensordiffeq_tpu.fleet.closedloop`).

        The candidate artifact is restored through the checksum-validated
        checkpoint path and warm-driven BESIDE the live tenant — the old
        engine keeps serving while the new one loads and compiles
        nothing at request time.  The canary then replays the pinned
        ``probe_X`` on both engines: the candidate's mean absolute
        residual must come in at or under the gate (``gate`` absolute
        when given, else ``gate_ratio`` × the OLD engine's replayed
        residual).  Only a passing candidate flips the route: the old
        engine's pending batches are flushed (zero dropped or hung
        waiters), the loaded-tenant entry is replaced in place (same LRU
        slot — the flip is one dict assignment), and the registration
        points at the new artifact so later reloads get v2.

        A candidate that fails to restore (torn blob → checksum
        mismatch) or fails its gate is REJECTED: the old engine keeps
        serving, and the probe replay after rejection is byte-compared
        against the pre-swap ``u`` snapshot (``bit_identical`` in the
        verdict) — rollback is proven, not assumed.

        Returns the verdict dict: ``swapped``, ``reason``,
        ``old_residual`` / ``new_residual`` / ``gate``,
        ``cutover_stall_s`` (flip-time flush stall; the only pause any
        waiter can observe), ``bit_identical`` (rejections only) and the
        candidate's warm-start report.

        With a tracer active the whole cutover is a ``fleet.hot_swap``
        span — opened under whatever trace the caller carries, so a
        retrain job's swap joins the retrain trace the
        :class:`~tensordiffeq_tpu.fleet.RetrainController` propagated."""
        tr = active_tracer()  # one probe on the untraced path
        if tr is None:
            return self._hot_swap(tenant, artifact, f_model=f_model,
                                  net=net, probe_X=probe_X, gate=gate,
                                  gate_ratio=gate_ratio)
        with tr.span("fleet.hot_swap", tenant=str(tenant),
                     artifact=str(artifact)) as sp:
            verdict = self._hot_swap(tenant, artifact, f_model=f_model,
                                     net=net, probe_X=probe_X, gate=gate,
                                     gate_ratio=gate_ratio)
            sp.set_attrs(swapped=bool(verdict.get("swapped")),
                         reason=str(verdict.get("reason")))
            if not verdict.get("swapped"):
                sp.status = "error"
            return verdict

    def _hot_swap(self, tenant: str, artifact: str, *, f_model=None,
                  net=None, probe_X=None, gate: Optional[float] = None,
                  gate_ratio: float = 1.0) -> dict:
        reg = self._reg(tenant)
        old = self.load(tenant)
        verdict: dict = {"tenant": str(tenant), "swapped": False,
                         "artifact": str(artifact)}
        probe = (None if probe_X is None
                 else np.atleast_2d(np.asarray(probe_X, np.float32)))
        u_before = (None if probe is None
                    else np.asarray(old.engine.u(probe)).tobytes())

        t0 = self._clock()
        try:
            sur = Surrogate.load(str(artifact), f_model=f_model, net=net)
            scope = self._registry.scope(tenant=tenant)
            engine = sur.engine(min_bucket=reg.policy.min_bucket,
                                max_bucket=reg.policy.max_bucket,
                                shard=reg.policy.shard, registry=scope)
            warm: dict = {}
            if reg.policy.warm_start:
                warm = warm_start(engine, kinds=reg.policy.warm_kinds,
                                  tenant=tenant, registry=self._registry,
                                  max_drive_bucket=reg.policy.max_batch)
        except Exception as e:
            # torn/corrupt candidate: the checkpoint checksum (or the
            # engine build) refused it — the old engine never stopped
            self._reject(tenant, old, probe, u_before, verdict,
                         reason="artifact_rejected",
                         detail=f"{type(e).__name__}: {e}")
            return verdict
        verdict["warm"] = warm
        verdict["candidate_load_s"] = self._clock() - t0

        if probe is not None:
            old_res = float(np.mean(np.abs(
                np.asarray(old.engine.residual(probe)))))
            new_res = float(np.mean(np.abs(
                np.asarray(engine.residual(probe)))))
            g = float(gate) if gate is not None else gate_ratio * old_res
            verdict.update(old_residual=old_res, new_residual=new_res,
                           gate=g)
            if not np.isfinite(new_res) or new_res > g:
                self._registry.counter("fleet.canary.rejected",
                                       tenant=tenant).inc()
                log_event("closedloop",
                          f"CANARY rejected tenant={tenant}: candidate "
                          f"|residual| {new_res:.3e} over gate {g:.3e} "
                          f"(old engine replays {old_res:.3e})",
                          level="warning", verbose=False, event="canary",
                          tenant=str(tenant), passed=False,
                          old_residual=old_res, new_residual=new_res,
                          gate=g)
                self._reject(tenant, old, probe, u_before, verdict,
                             reason="canary_regressed")
                return verdict
            self._registry.counter("fleet.canary.passed",
                                   tenant=tenant).inc()
            log_event("closedloop",
                      f"CANARY passed tenant={tenant}: candidate "
                      f"|residual| {new_res:.3e} within gate {g:.3e} "
                      f"(old engine replays {old_res:.3e})",
                      verbose=False, event="canary", tenant=str(tenant),
                      passed=True, old_residual=old_res,
                      new_residual=new_res, gate=g)

        # the atomic flip: flush what the OLD engine owes its waiters,
        # then replace the loaded entry in place — requests submitted
        # after this line batch against the (already warm) new engine
        t1 = self._clock()
        old.flush()
        self._loaded[tenant] = LoadedTenant(
            tenant, sur, engine, reg.policy, scope, self._clock, warm)
        reg.artifact = str(artifact)
        reg.f_model = f_model
        reg.net = net
        reg.quarantine = []  # old rungs' history does not apply to v2
        stall = self._clock() - t1
        self._registry.counter("fleet.swap.flips", tenant=tenant).inc()
        self._registry.histogram("fleet.swap.cutover_stall_s",
                                 tenant=tenant).observe(stall)
        verdict.update(swapped=True, reason="swapped",
                       cutover_stall_s=stall)
        log_event("closedloop",
                  f"SWAPPED tenant={tenant} to {artifact} "
                  f"(cutover stall {stall * 1e3:.2f}ms, warm start: "
                  f"{warm.get('aot', 0)} AOT + {warm.get('jit', 0)} jit)",
                  verbose=False, event="swap", tenant=str(tenant),
                  artifact=str(artifact), cutover_stall_s=stall)
        return verdict

    def _reject(self, tenant: str, old: LoadedTenant, probe, u_before,
                verdict: dict, *, reason: str,
                detail: Optional[str] = None) -> None:
        """Candidate rejection: record the rollback, and PROVE the old
        engine still serves bit-identically by replaying the probe
        against the pre-swap snapshot."""
        if probe is not None:
            u_after = np.asarray(old.engine.u(probe)).tobytes()
            verdict["bit_identical"] = u_after == u_before
        self._registry.counter("fleet.swap.rollbacks", tenant=tenant).inc()
        verdict.update(reason=reason, **({"detail": detail} if detail
                                         else {}))
        log_event("closedloop",
                  f"ROLLBACK: tenant={tenant} kept its old engine "
                  f"({reason}" + (f": {detail}" if detail else "")
                  + ("; probe replay bit-identical"
                     if verdict.get("bit_identical") else "") + ")",
                  level="warning", verbose=False, event="rollback",
                  tenant=str(tenant), reason=reason,
                  bit_identical=verdict.get("bit_identical"))

    # ------------------------------------------------------------------ #
    def submit(self, tenant: str, X, kind: str = "u",
               priority: Optional[int] = None):
        """Admission-gated submit: the request passes the
        :class:`AdmissionController` BEFORE the tenant is even loaded —
        overload never triggers artifact loads, let alone queue growth —
        then coalesces into the tenant's per-kind batcher.  Returns the
        batcher's :class:`~tensordiffeq_tpu.serving.PendingQuery` handle;
        raises :class:`~tensordiffeq_tpu.fleet.AdmissionRejected` when
        shed.  With a tracer active the admit → load-or-queue path is a
        ``fleet.submit`` span tree (nested under ``fleet.request`` when
        reached through :meth:`query`)."""
        tr = active_tracer()  # one probe on the untraced path
        if tr is None:
            return self._submit(tenant, X, kind, priority)
        with tr.span("fleet.submit", tenant=str(tenant), kind=str(kind)):
            return self._submit(tenant, X, kind, priority)

    def _submit(self, tenant: str, X, kind: str, priority: Optional[int]):
        reg = self._reg(tenant)  # unknown tenants fail before admission
        n = int(np.atleast_2d(np.asarray(X)).shape[0])
        lt = self._loaded.get(tenant)
        self.admission.admit(
            tenant, n,
            priority if priority is not None else reg.policy.priority,
            tenant_pending=0 if lt is None else lt.pending_points(),
            fleet_pending=self.pending_points())
        return self.load(tenant).batcher(kind).submit(X)

    def query(self, tenant: str, X, kind: str = "u",
              priority: Optional[int] = None):
        """Blocking convenience: submit, flush, return the rows.  With no
        chaos active the result is bit-identical to the same call on a
        direct engine over the same artifact; with a tracer active the
        whole request is one ``fleet.request`` span tree — admission →
        load → batcher enqueue/flush → engine run → dispatch/device —
        the end-to-end trace the run log keeps per query."""
        tr = active_tracer()
        if tr is None:
            handle = self.submit(tenant, X, kind=kind, priority=priority)
            self._loaded[tenant].batcher(kind).flush()
            return handle.result()
        with tr.span("fleet.request", tenant=str(tenant), kind=str(kind)):
            handle = self.submit(tenant, X, kind=kind, priority=priority)
            self._loaded[tenant].batcher(kind).flush()
            return handle.result()

    def poll(self) -> bool:
        """Deadline sweep over every live tenant's batchers (hosts call
        this from their event loop).  Returns whether anything flushed."""
        return any([lt.poll() for lt in list(self._loaded.values())])

    def flush(self, tenant: Optional[str] = None) -> None:
        """Flush pending batches — one tenant's, or every live tenant's.
        An unknown tenant raises ``KeyError`` like every sibling method
        (a misspelled name must not masquerade as a successful flush);
        a registered-but-unloaded tenant has nothing pending and no-ops."""
        if tenant is None:
            targets = list(self._loaded.values())
        else:
            self._reg(tenant)
            lt = self._loaded.get(tenant)
            targets = [lt] if lt is not None else []
        for lt in targets:
            lt.flush()

    def pending_points(self) -> int:
        return sum(lt.pending_points() for lt in self._loaded.values())

    # ------------------------------------------------------------------ #
    def serve_metrics(self, addr: str = "127.0.0.1", port: int = 0, *,
                      slos=None, run_dirs: Sequence[str] = (),
                      host: Optional[str] = None):
        """One-call observability mount: a
        :class:`~tensordiffeq_tpu.telemetry.Collector` exposing this
        router's registry (every ``fleet.*`` / ``serving.*`` instrument,
        per-tenant labels included) plus any ``run_dirs`` to tail,
        served at ``/metrics`` + ``/healthz``.  ``/healthz`` evaluates
        ``slos`` (default: this router's own :class:`SLOSet`) over the
        merged fleet view.  Returns the collector (its ``.url`` is the
        scrape target); caller closes it."""
        import os as _os
        import socket as _socket

        from ..telemetry.collector import Collector
        label = host if host is not None else _socket.gethostname()
        c = Collector(slos=slos if slos is not None else self.slo)
        c.attach_registry(self._registry, host=label,
                          process=f"router:{_os.getpid()}")
        for d in run_dirs:
            c.watch(d, host=label)
        c.serve(addr, port)
        self.collector = c
        return c

    def drain(self) -> int:
        """Planned-shutdown drain: flush every live tenant's pending
        batches, then fail-fast whatever could not execute (open
        breakers) — the zero-dropped-waiter contract :meth:`hot_swap`
        applies to one engine flip, applied to the whole process.  A
        replica worker calls this BEFORE exiting so in-flight
        ``PendingQuery`` handles complete instead of dying with the
        process.  Returns the pending-point count that was outstanding
        when the drain began."""
        owed = self.pending_points()
        for lt in list(self._loaded.values()):
            lt.drain()
        log_event("fleet", f"drained {owed} pending point(s) across "
                  f"{len(self._loaded)} live tenant(s)", verbose=False,
                  event="drain", pending_points=owed)
        return owed

    # ------------------------------------------------------------------ #
    def stats(self) -> dict:
        """Cache tallies + per-tenant load state and batcher stats.
        Built from ONE capture of the loaded-tenant table and one
        :meth:`LoadedTenant.snapshot` per live tenant, so a flush racing
        the scrape (the replica beat thread, a collector poll) cannot
        tear the per-tenant numbers mid-read."""
        loaded = dict(self._loaded)
        tenants = {}
        for t in self._registered:
            lt = loaded.get(t)
            if lt is None:
                tenants[t] = {"loaded": False}
                continue
            tenants[t] = {
                "loaded": True,
                "kinds": lt.snapshot()["kinds"],
                "quarantined": lt.engine.quarantined_buckets(),
                "warm": lt.warm,
            }
        return {
            "max_loaded": self.max_loaded,
            "hits": self._hits, "misses": self._misses,
            "evictions": self._evictions,
            "tenants": tenants,
        }

    def autoscale_signals(self) -> dict:
        """The scale-up/down inputs an operator loop polls: per-tenant
        queue depth and latency percentiles, fleet-level cache pressure
        (a high eviction rate with a full cache is the 'add a replica /
        raise max_loaded' signal; all-zero queue depths with idle
        tenants is the scale-down one), and the :class:`SLOSet` verdict
        over the router's registry — scale on burn rate before the
        breach, not after.  One :meth:`LoadedTenant.snapshot` per tenant
        feeds BOTH the per-tenant rows and the fleet ``pending_points``
        total, so the total always equals the sum of the reported queue
        depths even while batchers flush concurrently."""
        tenants = {}
        fleet_pending = 0
        for t, lt in tuple(self._loaded.items()):
            snap = lt.snapshot()
            agg = snap["kinds"]
            lat = [s["latency_s"] for s in agg.values()
                   if s.get("latency_s", {}).get("p99") is not None]
            tenants[t] = {
                "queue_depth": snap["pending_points"],
                "qps": sum(s["qps"] or 0.0 for s in agg.values()),
                "latency_p99_s": max((p["p99"] for p in lat),
                                     default=None),
                "breaker": None if lt.breaker is None else lt.breaker.state,
            }
            fleet_pending += snap["pending_points"]
        total = self._hits + self._misses
        return {
            "loaded": len(tenants), "max_loaded": self.max_loaded,
            "cache_hit_rate": (self._hits / total) if total else None,
            "evictions": self._evictions,
            "pending_points": fleet_pending,
            "tenants": tenants,
            "slo": self.slo.evaluate(self._registry),
        }
