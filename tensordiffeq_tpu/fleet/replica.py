"""Replicated serving plane: a fleet of fleets behind one front door.

One :class:`~tensordiffeq_tpu.fleet.FleetRouter` process dying takes
every tenant's queries with it — the training plane earned gang restart
and chaos drills in PRs 5/8/18 while the serving plane stayed a single
point of failure.  This module is the missing half:

* a **ReplicaGroup** runs N replica worker processes, each a full
  :class:`FleetRouter` serving the complete tenant set warm-started from
  the shared artifact directory (zero request-time compiles — the PR 6
  AOT ladder).  The PR 8 :class:`~tensordiffeq_tpu.resilience.
  ClusterSupervisor` supervises them in its serving-plane
  ``relaunch_scope="worker"`` mode: progress heartbeats become
  liveness+readiness beats (queue depth, loaded tenants, last-flush
  age), a stale beat or non-0 exit is a lost replica, and the relaunch
  respawns ONLY that slot in place — its peers keep serving untouched.
* a **ReplicaServer** wraps one worker's router behind stdlib HTTP
  (the PR 19 collector pattern): ``POST /query`` (base64-exact arrays —
  chaos-off replicated serving is bit-identical to a direct router),
  ``POST /drain`` / ``POST /shutdown`` (every in-flight
  :class:`~tensordiffeq_tpu.serving.PendingQuery` completes before the
  worker exits — ``hot_swap``'s zero-dropped-waiter contract applied to
  a process), ``GET /healthz`` / ``GET /metrics``.  A beat thread
  publishes the heartbeat AND an atomic ``metrics.live.json`` registry
  snapshot, so the fleet collector scrapes a replica's counters while it
  is alive, not just after its RunLogger finalizes.
* a **FrontRouter** hashes tenants onto replicas with RENDEZVOUS hashing
  — each (tenant, replica) pair gets an order-free hash weight and the
  tenant routes to its top-weighted live replica, so losing one replica
  remaps only that replica's ~1/N of tenants (consistent-hash bound)
  while everyone else's routes are untouched.  It owns the
  request-level robustness ladder: a per-replica
  :class:`~tensordiffeq_tpu.resilience.CircuitBreaker` (transport
  failures only — a tenant's own breaker opening on a replica must
  never open the replica's) with
  :class:`~tensordiffeq_tpu.resilience.RetryPolicy` failover to the next
  hash candidate, deadline-bounded sweeps, opt-in hedged retries for
  tail tolerance, and graceful degradation below quorum (the
  :class:`~tensordiffeq_tpu.fleet.AdmissionController` watermarks
  tighten via :meth:`AdmissionController.degrade`).

The liveness/reachability split is deliberate: the supervisor's beats
see a DEAD or HUNG replica (process-level), the front router's breaker
sees an UNREACHABLE one (the chaos ``replica_net_partition`` case —
alive, beating, dropping requests).  Both paths are chaos-drilled in
``tests/test_replica.py``; ``bench.py --mode fleetha`` prices the
failover (p99, zero lost requests, recovery wall).
"""

from __future__ import annotations

import base64
import hashlib
import http.client
import http.server
import json
import os
import socket
import sys
import threading
import time
import urllib.parse
from typing import Callable, Optional, Sequence

import numpy as np

from ..resilience.breaker import CircuitBreaker, CircuitOpenError
from ..resilience.chaos import active_chaos
from ..resilience.cluster import ClusterSupervisor, beat, free_port
from ..resilience.retry import RetryPolicy
from ..serving.batcher import RequestTimeout
from ..telemetry import default_registry, log_event
from ..telemetry.collector import SNAPSHOT_FILE
from ..telemetry.slo import to_prometheus
from ..telemetry.tracing import active_tracer
from .admission import AdmissionController, AdmissionRejected


# -------------------------------------------------------------------------- #
# wire codecs: exact-bytes arrays over JSON
# -------------------------------------------------------------------------- #
def encode_array(arr) -> dict:
    """An array as ``{"b64", "dtype", "shape"}`` — base64 of the raw
    bytes, NOT a decimal rendering, so a round-trip is bit-exact (the
    chaos-off replicated serve must be bit-identical to a direct
    router)."""
    a = np.ascontiguousarray(np.asarray(arr))
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "dtype": str(a.dtype), "shape": list(a.shape)}


def decode_array(block: dict) -> np.ndarray:
    """Inverse of :func:`encode_array` (a writable copy — ``frombuffer``
    alone would alias the decode buffer read-only)."""
    a = np.frombuffer(base64.b64decode(block["b64"]),
                      dtype=np.dtype(str(block["dtype"])))
    return a.reshape([int(s) for s in block["shape"]]).copy()


def _encode_result(result) -> dict:
    if isinstance(result, tuple):
        return {"tuple": [encode_array(r) for r in result]}
    return encode_array(result)


def _decode_result(block: dict):
    if "tuple" in block:
        return tuple(decode_array(b) for b in block["tuple"])
    return decode_array(block)


# -------------------------------------------------------------------------- #
# errors
# -------------------------------------------------------------------------- #
class ReplicaUnavailable(RuntimeError):
    """Every hash candidate was down, breaker-open, or out of deadline —
    the front router exhausted its failover ladder.  ``trail`` records
    what each attempt saw (for the incident report)."""

    trace_id = None

    def __init__(self, tenant: str, trail: Sequence[str] = ()):
        self.tenant = str(tenant)
        self.trail = tuple(str(t) for t in trail)
        super().__init__(
            f"no replica could serve tenant {tenant!r}: "
            + ("; ".join(self.trail) if self.trail else "no candidates"))


class ReplicaRequestError(RuntimeError):
    """A replica answered with a structured non-retryable failure the
    front router has no richer type for (HTTP 500 relay).  The replica
    is HEALTHY — transport worked — so this never counts against its
    breaker."""

    trace_id = None

    def __init__(self, replica: str, status: int, detail: str):
        self.replica = str(replica)
        self.status = int(status)
        super().__init__(
            f"replica {replica!r} failed the request (HTTP {status}): "
            f"{detail}")


class _ReplicaCallError(Exception):
    """Private transport-level marker: connection refused/reset/dropped,
    malformed response, or an explicit drain — the retryable class that
    DOES count against the replica's breaker and triggers failover."""


def _http_json(method: str, base_url: str, path: str,
               payload: Optional[dict] = None,
               timeout: float = 10.0) -> tuple:
    """One stdlib-HTTP JSON exchange: ``(status, parsed_body)``.
    Transport failures raise ``OSError`` / ``http.client.HTTPException``
    — the caller maps them (the front router onto its breaker)."""
    u = urllib.parse.urlsplit(str(base_url))
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=timeout)
    try:
        body = None if payload is None else json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"} if body else {}
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        try:
            parsed = json.loads(data.decode("utf-8")) if data else {}
        except (UnicodeDecodeError, json.JSONDecodeError):
            parsed = {}
        return resp.status, parsed
    finally:
        conn.close()


# -------------------------------------------------------------------------- #
# the replica worker: one FleetRouter behind HTTP + liveness beats
# -------------------------------------------------------------------------- #
class ReplicaServer:
    """One replica: a :class:`FleetRouter` served over stdlib HTTP with
    liveness+readiness beats (see module docstring).

    The router (and its batchers) is single-threaded by design, so every
    router touch from the concurrent HTTP handler threads serializes
    under one lock — the coalescing window, not the lock, stays the
    batching mechanism.

    Endpoints: ``POST /query`` (``{"tenant", "kind", "x": enc[,
    "priority"]}`` → ``{"ok": true, "result": enc}`` or a structured
    error body — 429 admission, 503 tenant-breaker/draining, 504
    deadline, 404 unknown tenant), ``POST /drain`` (flush + fail-fast
    all pending; the replica rejects queries afterwards), ``POST
    /shutdown`` (drain, answer, then exit 0), ``GET /ping`` /
    ``/healthz`` / ``/metrics``.
    """

    def __init__(self, router, *, rank: int = 0, port: int = 0,
                 addr: str = "127.0.0.1", run_dir: Optional[str] = None,
                 beat_interval_s: float = 0.5, tracer=None, registry=None):
        self.router = router
        self.rank = int(rank)
        self.addr = str(addr)
        self.port = int(port)
        self.run_dir = None if run_dir is None else str(run_dir)
        self.beat_interval_s = float(beat_interval_s)
        self.tracer = tracer
        self._registry = (registry if registry is not None
                          else router._registry)
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._draining = False
        self._n_requests = 0
        self._last_flush_wall: Optional[float] = None
        self._httpd = None
        self._http_thread = None
        self._beat_thread = None

    # ------------------------------------------------------------------ #
    def handle_query(self, payload: dict) -> tuple:
        """One query: ``(status, body)`` — or ``(None, None)`` when chaos
        says this replica is partitioned and the connection must drop
        unanswered (the fault liveness beats cannot see)."""
        with self._lock:
            self._n_requests += 1
            n = self._n_requests
        ch = active_chaos()
        if ch is not None and ch.on_replica_request(n, rank=self.rank):
            return None, None
        if self._draining:
            return 503, {"error": "draining", "rank": self.rank}
        self._registry.counter("fleet.replica.requests").inc()
        try:
            tenant = payload["tenant"]
            kind = payload.get("kind", "u")
            X = decode_array(payload["x"])
            with self._lock:
                result = self.router.query(
                    tenant, X, kind=kind, priority=payload.get("priority"))
                self._last_flush_wall = time.time()
            return 200, {"ok": True, "result": _encode_result(result)}
        except AdmissionRejected as e:
            return 429, {"error": "AdmissionRejected", "tenant": e.tenant,
                         "reason": e.reason,
                         "retry_after_s": e.retry_after_s}
        except CircuitOpenError as e:
            return 503, {"error": "CircuitOpenError", "breaker": e.breaker,
                         "retry_after_s": e.retry_after_s}
        except RequestTimeout as e:
            return 504, {"error": "RequestTimeout", "waited_s": e.waited_s}
        except KeyError as e:
            return 404, {"error": "KeyError", "detail": str(e)}
        except Exception as e:
            return 500, {"error": type(e).__name__, "detail": str(e)}

    def drain(self) -> int:
        """Flush + fail-fast everything pending and refuse new queries
        from here on (the worker's half of the drain-before-exit
        contract).  Returns the pending points outstanding at entry."""
        with self._lock:
            self._draining = True
            return self.router.drain()

    def readiness(self) -> dict:
        with self._lock:
            return {"ok": True, "ready": not self._draining,
                    "rank": self.rank, "draining": self._draining,
                    "tenants": list(self.router.loaded()),
                    "pending_points": self.router.pending_points(),
                    "requests": self._n_requests}

    # ------------------------------------------------------------------ #
    def _beat_once(self) -> None:
        with self._lock:
            pending = self.router.pending_points()
            loaded = len(self.router.loaded())
            n = self._n_requests
            last = self._last_flush_wall
        age = -1.0 if last is None else time.time() - last
        # liveness+readiness beat: the supervisor reads the mtime, humans
        # tailing the dir read the payload.  NO spaces inside the phase —
        # the supervisor's sampler whitespace-splits the beat line.
        beat(f"serve[q={pending},t={loaded},flush={age:.1f}]", n)
        self._write_live_metrics()

    def _write_live_metrics(self) -> None:
        """Atomically publish the live registry snapshot the fleet
        collector prefers over a not-yet-final manifest."""
        if self.run_dir is None:
            return
        tmp = os.path.join(self.run_dir, SNAPSHOT_FILE + ".tmp")
        try:
            with open(tmp, "w") as fh:
                json.dump({"metrics": self._registry.as_dict()}, fh)
            os.replace(tmp, os.path.join(self.run_dir, SNAPSHOT_FILE))
        except (OSError, TypeError, ValueError):
            pass  # a failing snapshot must never kill serving

    def _beat_loop(self) -> None:
        while not self._done.is_set():
            self._beat_once()
            self._done.wait(self.beat_interval_s)
        self._beat_once()  # final beat + snapshot before exit

    # ------------------------------------------------------------------ #
    def serve(self) -> str:
        """Start the HTTP endpoint + beat thread; returns the URL."""
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code: int, body: dict,
                      raw: Optional[bytes] = None,
                      ctype: str = "application/json"):
                data = (raw if raw is not None
                        else (json.dumps(body) + "\n").encode("utf-8"))
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                ch = active_chaos()
                if ch is not None and ch.replica_partition_active():
                    self.close_connection = True
                    return  # partitioned: unreachable, not unhealthy
                if path == "/ping":
                    self._send(200, {"ok": True, "rank": server.rank})
                elif path == "/healthz":
                    self._send(200, server.readiness())
                elif path == "/metrics":
                    self._send(200, {}, raw=to_prometheus(
                        server._registry).encode("utf-8"),
                        ctype="text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._send(404, {"error": "not_found", "path": path})

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b""
                try:
                    payload = json.loads(raw.decode("utf-8")) if raw else {}
                except (UnicodeDecodeError, json.JSONDecodeError):
                    self._send(400, {"error": "bad_json"})
                    return
                if path == "/query":
                    code, body = server.handle_query(payload)
                    if code is None:  # chaos partition: drop unanswered
                        self.close_connection = True
                        return
                    self._send(code, body)
                elif path == "/drain":
                    self._send(200, {"ok": True,
                                     "drained_points": server.drain()})
                elif path == "/shutdown":
                    n = server.drain()
                    self._send(200, {"ok": True, "drained_points": n})
                    server._done.set()  # answered first, THEN exit
                else:
                    self._send(404, {"error": "not_found", "path": path})

            def log_message(self, *args):
                pass  # replica stdout stays clean for the log files

        self._httpd = http.server.ThreadingHTTPServer(
            (self.addr, self.port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="tdq-replica",
            daemon=True)
        self._http_thread.start()
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name="tdq-replica-beat", daemon=True)
        self._beat_thread.start()
        log_event("replica", f"replica rank {self.rank} serving "
                  f"{len(self.router.tenants())} tenant(s) at {self.url}",
                  verbose=False, rank=self.rank, url=self.url)
        return self.url

    @property
    def url(self) -> Optional[str]:
        if self._httpd is None:
            return None
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until a ``/shutdown`` (or :meth:`close`)."""
        return self._done.wait(timeout)

    def close(self) -> None:
        self._done.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for t in (self._http_thread, self._beat_thread):
            if t is not None:
                t.join(timeout=5.0)
        self._http_thread = self._beat_thread = None

    def __enter__(self) -> "ReplicaServer":
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# -------------------------------------------------------------------------- #
# the worker entry point (python -m tensordiffeq_tpu.fleet.replica)
# -------------------------------------------------------------------------- #
def main(argv: Optional[Sequence[str]] = None) -> None:
    """Replica worker: import the bootstrap (``module:callable`` →
    :class:`FleetRouter`), preload EVERY registered tenant (warm start —
    the first beat only happens once the replica can answer its first
    query with zero request-time compiles), then serve until
    ``/shutdown``.  Runs under a RunLogger + env-inherited Tracer so its
    spans join the supervisor's stitched trace."""
    import argparse
    import importlib

    p = argparse.ArgumentParser(prog="tensordiffeq_tpu.fleet.replica")
    p.add_argument("--rank", type=int, required=True)
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--bootstrap", required=True,
                   help="module:callable returning a registered FleetRouter")
    p.add_argument("--run-dir", default=None)
    p.add_argument("--beat-interval", type=float, default=0.5)
    args = p.parse_args(argv)

    mod_name, sep, fn_name = args.bootstrap.partition(":")
    if not sep or not fn_name:
        raise ValueError(
            f"--bootstrap must be module:callable, got {args.bootstrap!r}")
    router = getattr(importlib.import_module(mod_name), fn_name)()

    gen = os.environ.get("TDQ_CLUSTER_GENERATION", "0")
    run_dir = args.run_dir or os.path.join(
        os.getcwd(), f"replica{args.rank}.gen{gen}")

    from ..telemetry.runlog import RunLogger
    from ..telemetry.tracing import Tracer
    with RunLogger(run_dir, config={"rank": args.rank, "port": args.port,
                                    "generation": gen},
                   registry=router._registry) as lg:
        tracer = Tracer.from_env(logger=lg, registry=router._registry)
        with tracer:
            for t in router.tenants():
                router.load(t)  # warm start BEFORE the first beat
            server = ReplicaServer(
                router, rank=args.rank, port=args.port, run_dir=run_dir,
                beat_interval_s=args.beat_interval, tracer=tracer)
            server.serve()
            server.wait()
            server.close()


# -------------------------------------------------------------------------- #
# the replica group: ClusterSupervisor repurposed for serving
# -------------------------------------------------------------------------- #
class ReplicaGroup:
    """N replica workers under a serving-mode
    :class:`~tensordiffeq_tpu.resilience.ClusterSupervisor`
    (``relaunch_scope="worker"``: a lost replica is respawned in place
    while its peers keep serving).

    Ports are allocated ONCE per slot and pinned across relaunches, so a
    respawned replica comes back at the same endpoint and the front
    router's breaker simply half-opens back into it — no re-discovery.

    Args:
      bootstrap: ``module:callable`` importable IN THE WORKER that
        returns a registered :class:`FleetRouter` (artifact paths must
        be absolute or resolvable from ``workdir`` — the workers run
        there).
      nproc: replica count.
      workdir: heartbeat files, worker logs and per-replica run dirs
        (``replica<r>.gen<g>``) land here.
      heartbeat_timeout_s: stale-beat bound; must exceed the worker's
        startup (imports + artifact load + warm start) since beats only
        start once the replica can serve.
      env: extra worker environment (e.g. ``PYTHONPATH`` for the
        bootstrap module, or a ``TDQ_CHAOS`` spec).
    """

    def __init__(self, bootstrap: str, nproc: int = 2,
                 workdir: str = "replicas", *,
                 heartbeat_timeout_s: float = 120.0,
                 max_relaunches: int = 2, beat_interval_s: float = 0.5,
                 poll_s: float = 0.2, env: Optional[dict] = None,
                 tracer=None, registry=None, verbose: bool = False):
        self.bootstrap = str(bootstrap)
        self.nproc = int(nproc)
        self.workdir = str(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.ports = [free_port() for _ in range(self.nproc)]
        self.registry = (registry if registry is not None
                         else default_registry())
        beat_iv = float(beat_interval_s)

        def worker_cmd(pid: int, nproc_: int, port_: int) -> list:
            # the supervisor's per-generation port is for collective
            # jobs; replicas pin their slot's stable port instead.  -c
            # instead of -m: the fleet package imports this module, so
            # runpy's -m re-execution would warn about the double import.
            return [sys.executable, "-c",
                    "from tensordiffeq_tpu.fleet.replica import main; "
                    "main()",
                    "--rank", pid, "--port", self.ports[pid],
                    "--bootstrap", self.bootstrap,
                    "--beat-interval", beat_iv]

        self.supervisor = ClusterSupervisor(
            worker_cmd, self.nproc, self.workdir,
            heartbeat_timeout_s=heartbeat_timeout_s, poll_s=poll_s,
            grace_s=5.0, max_relaunches=max_relaunches, min_hosts=1,
            env=env, tracer=tracer, registry=self.registry,
            verbose=verbose, relaunch_scope="worker")
        self.collector = None  # set by serve_metrics
        self._pool = None
        self._future = None

    # ------------------------------------------------------------------ #
    def endpoints(self) -> dict:
        """``{replica_name: base_url}`` — the FrontRouter's input."""
        return {f"replica{i}": f"http://127.0.0.1:{p}"
                for i, p in enumerate(self.ports)}

    def run_dirs(self) -> list:
        """Every per-replica run dir (all generations), for trace
        stitching and collector tails — includes dirs that do not exist
        YET (future relaunch generations), which both consumers
        tolerate."""
        return [os.path.join(self.workdir, f"replica{r}.gen{g}")
                for r in range(self.nproc)
                for g in range(self.supervisor.max_relaunches + 1)]

    def start(self, timeout_s: float = 600.0) -> None:
        """Launch the group (the supervisor loop runs on a worker
        thread; :meth:`shutdown` joins it)."""
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(1)
        self._future = self._pool.submit(self.supervisor.run, timeout_s)

    def wait_ready(self, timeout_s: float = 120.0,
                   min_replicas: Optional[int] = None) -> dict:
        """Block until ``min_replicas`` (default: all) answer
        ``/healthz`` ready; returns ``{name: readiness}``.  Raises the
        supervisor's failure immediately if the group died first."""
        need = self.nproc if min_replicas is None else int(min_replicas)
        deadline = time.monotonic() + float(timeout_s)
        eps = self.endpoints()
        while True:
            if self._future is not None and self._future.done():
                self._future.result()  # surfaces HostLost etc.
                raise ReplicaUnavailable(
                    "*", [f"supervisor exited before {need} replica(s) "
                          "became ready"])
            ready = {}
            for name, url in eps.items():
                try:
                    status, body = _http_json("GET", url, "/healthz",
                                              timeout=2.0)
                except (OSError, http.client.HTTPException):
                    continue
                if status == 200 and body.get("ready"):
                    ready[name] = body
            if len(ready) >= need:
                return ready
            if time.monotonic() > deadline:
                raise ReplicaUnavailable(
                    "*", [f"only {len(ready)}/{need} replica(s) ready "
                          f"after {timeout_s:.0f}s"])
            time.sleep(0.1)

    def shutdown(self, timeout_s: float = 60.0):
        """Drain-then-exit every replica (zero dropped waiters), join
        the supervisor, return its
        :class:`~tensordiffeq_tpu.resilience.ClusterResult`.

        The ``/shutdown`` POSTs repeat until the supervisor joins: a
        slot that is mid-respawn when shutdown starts is not listening
        YET (the POST fails silently), and a single-shot goodbye would
        leave it serving forever while the join times out."""
        from concurrent.futures import TimeoutError as FuturesTimeout
        deadline = time.monotonic() + float(timeout_s)
        result = None
        while True:
            for url in self.endpoints().values():
                try:
                    _http_json("POST", url, "/shutdown", payload={},
                               timeout=5.0)
                except (OSError, http.client.HTTPException):
                    pass  # dead or not up yet — retried next lap
            if self._future is None:
                break
            try:
                result = self._future.result(timeout=min(
                    2.0, max(0.1, deadline - time.monotonic())))
                break
            except FuturesTimeout:
                if time.monotonic() > deadline:
                    raise
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self._pool = self._future = None
        return result

    def serve_metrics(self, addr: str = "127.0.0.1", port: int = 0, *,
                      slos=None, host: Optional[str] = None):
        """One fleet-wide scrape target: a
        :class:`~tensordiffeq_tpu.telemetry.Collector` merging the
        supervisor's registry with every replica run dir (their beat
        threads publish live ``metrics.live.json`` snapshots, so replica
        counters show up while the replicas run).  Attach the front
        router's registry too (``collector.attach_registry``) to fold in
        availability/failover instruments."""
        from ..telemetry.collector import Collector
        label = host if host is not None else socket.gethostname()
        c = Collector(slos=slos)
        c.attach_registry(self.supervisor.registry, host=label,
                          process=f"supervisor:{os.getpid()}")
        for d in self.run_dirs():
            c.watch(d, host=label)
        c.serve(addr, port)
        self.collector = c
        return c


# -------------------------------------------------------------------------- #
# the front tier: rendezvous hashing + breaker/retry failover
# -------------------------------------------------------------------------- #
def _rendezvous_weight(tenant: str, name: str) -> int:
    h = hashlib.blake2b(f"{tenant}|{name}".encode("utf-8"), digest_size=8)
    return int.from_bytes(h.digest(), "big")


class FrontRouter:
    """Hash tenants onto replicas; own the request-level robustness
    ladder (see module docstring).

    Args:
      replicas: ``{name: base_url}`` (a :meth:`ReplicaGroup.endpoints`).
      retry: failover pacing BETWEEN candidate sweeps — ``max_attempts``
        bounds the sweeps, ``delay_s`` the inter-sweep backoff.
      breaker_failure_threshold / breaker_reset_timeout_s: the
        per-replica breaker.  The default threshold of 1 is deliberate:
        one TRANSPORT failure (connection refused/reset/dropped) opens
        the breaker, because unlike a tenant op there is no partial
        failure mode — and the half-open probe re-admits the replica the
        moment it answers again.
      deadline_s: default end-to-end budget per query (sweeps + backoff).
      call_timeout_s: per-HTTP-call socket timeout.
      hedge_after_s: opt-in tail tolerance — when the primary attempt
        has not resolved after this long, a second attempt starts on the
        rotated candidate list and the first success wins.
      quorum: live replicas required for nominal admission (default:
        majority).  Below it, ``admission.degrade(degrade_factor)``
        tightens the watermarks; back at quorum, ``restore()``.
      admission: the :class:`AdmissionController` to degrade (optional —
        without one, quorum loss is only surfaced via signals).
    """

    def __init__(self, replicas: dict, *,
                 retry: Optional[RetryPolicy] = None,
                 breaker_failure_threshold: int = 1,
                 breaker_reset_timeout_s: float = 1.0,
                 deadline_s: float = 10.0, call_timeout_s: float = 10.0,
                 hedge_after_s: Optional[float] = None,
                 quorum: Optional[int] = None,
                 admission: Optional[AdmissionController] = None,
                 degrade_factor: float = 0.5, registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        if not replicas:
            raise ValueError("FrontRouter needs at least one replica")
        self.replicas = {str(k): str(v) for k, v in replicas.items()}
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, base_delay_s=0.02, max_delay_s=0.2)
        self.deadline_s = float(deadline_s)
        self.call_timeout_s = float(call_timeout_s)
        self.hedge_after_s = (None if hedge_after_s is None
                              else float(hedge_after_s))
        self.quorum = (len(self.replicas) // 2 + 1 if quorum is None
                       else int(quorum))
        self.admission = admission
        self.degrade_factor = float(degrade_factor)
        self._registry = (registry if registry is not None
                          else default_registry())
        self._clock = clock
        self._sleep = sleep
        self._breakers = {
            name: CircuitBreaker(
                failure_threshold=breaker_failure_threshold,
                reset_timeout_s=breaker_reset_timeout_s,
                name=f"replica.{name}", clock=clock,
                registry=self._registry)
            for name in self.replicas}
        self._degraded = False
        self._hedge_pool = None
        self._update_availability()

    # ------------------------------------------------------------------ #
    def candidates(self, tenant: str) -> list:
        """Rendezvous order: every replica weighted by
        ``blake2b(tenant|name)``, highest first.  Removing one replica
        only re-homes the tenants whose TOP weight it held (~1/N of
        them); every other tenant's order is untouched — the remap bound
        consistent hashing promises, with no ring state to maintain."""
        return sorted(self.replicas,
                      key=lambda name: _rendezvous_weight(tenant, name),
                      reverse=True)

    def availability(self) -> float:
        """Fraction of replicas whose breaker is not open."""
        n = len(self._breakers)
        up = sum(1 for b in self._breakers.values() if b.state != "open")
        return up / n if n else 0.0

    def _update_availability(self) -> None:
        avail = self.availability()
        self._registry.gauge("fleet.replica.availability").set(avail)
        if self.admission is None:
            return
        up = round(avail * len(self._breakers))
        if up < self.quorum and not self._degraded:
            self._degraded = True
            self.admission.degrade(self.degrade_factor)
        elif up >= self.quorum and self._degraded:
            self._degraded = False
            self.admission.restore()

    # ------------------------------------------------------------------ #
    def _call(self, name: str, payload: dict, timeout: float):
        """One HTTP attempt against one replica; maps the wire protocol
        back onto the package's native exceptions.  Only TRANSPORT
        failures (and an explicit drain) become :class:`_ReplicaCallError`
        — a tenant-scoped error relayed by a healthy replica must never
        look like a dead replica."""
        try:
            status, body = _http_json("POST", self.replicas[name],
                                      "/query", payload, timeout=timeout)
        except (OSError, http.client.HTTPException) as e:
            raise _ReplicaCallError(
                f"{type(e).__name__}: {e}") from e
        if status == 200 and body.get("ok"):
            return _decode_result(body["result"])
        err = body.get("error")
        if status == 503 and err == "draining":
            raise _ReplicaCallError("draining")
        if status == 503 and err == "CircuitOpenError":
            raise CircuitOpenError(body.get("breaker", "fleet"),
                                   float(body.get("retry_after_s") or 0.0))
        if status == 429:
            raise AdmissionRejected(
                body.get("tenant", payload.get("tenant", "?")),
                body.get("reason", "rejected"),
                float(body.get("retry_after_s") or 0.0))
        if status == 504:
            raise RequestTimeout(float(body.get("waited_s") or 0.0))
        if status == 404:
            raise KeyError(body.get("detail") or payload.get("tenant"))
        raise ReplicaRequestError(name, status,
                                  f"{err}: {body.get('detail', '')}")

    def _sweep(self, tenant: str, payload: dict, deadline_t: float,
               cands: Sequence[str], trail: list):
        """Deadline-bounded failover sweeps over the candidate list.
        Transport failures burn the replica's breaker and move on; a
        structured error from a replica that ANSWERED re-raises (and
        counts as breaker success — the replica is reachable)."""
        tr = active_tracer()
        sweep = 0
        while True:
            tried_any = False
            for name in cands:
                br = self._breakers[name]
                if self._clock() >= deadline_t:
                    break
                if not br.allow():
                    trail.append(f"{name}: breaker open")
                    if tr is not None:
                        tr.record_span("fleet.front.breaker_open",
                                       duration_s=0.0, status="error",
                                       replica=name, tenant=str(tenant))
                    continue
                tried_any = True
                timeout = min(self.call_timeout_s,
                              max(0.05, deadline_t - self._clock()))
                try:
                    out = self._call(name, payload, timeout)
                except _ReplicaCallError as e:
                    br.record_failure()
                    self._registry.counter("fleet.failover.attempts",
                                           replica=name).inc()
                    trail.append(f"{name}: {e}")
                    self._update_availability()
                    continue
                except Exception:
                    br.record_success()  # reachable; error is the answer
                    self._update_availability()
                    raise
                br.record_success()
                self._update_availability()
                if name != cands[0]:
                    self._registry.counter("fleet.failover.reroutes").inc()
                    if tr is not None:
                        tr.record_span("fleet.front.reroute",
                                       duration_s=0.0, replica=name,
                                       tenant=str(tenant))
                return out
            sweep += 1
            if self._clock() >= deadline_t \
                    or sweep >= self.retry.max_attempts or not tried_any:
                self._registry.counter("fleet.failover.unavailable").inc()
                raise ReplicaUnavailable(tenant, trail)
            self._sleep(min(self.retry.delay_s(sweep),
                            max(0.0, deadline_t - self._clock())))

    # ------------------------------------------------------------------ #
    def query(self, tenant: str, X, kind: str = "u", *,
              deadline_s: Optional[float] = None,
              priority: Optional[int] = None):
        """Route one query: encode once, sweep the tenant's rendezvous
        candidates under the deadline, return the decoded rows (bit-
        identical to a direct router with no chaos active).  With a
        tracer active the whole thing is one ``fleet.front.request``
        span — breaker-open and reroute events land inside it, so a
        failover incident reads as one timeline in the stitched
        trace."""
        tr = active_tracer()
        if tr is None:
            return self._query(tenant, X, kind, deadline_s, priority)
        with tr.span("fleet.front.request", tenant=str(tenant),
                     kind=str(kind)):
            return self._query(tenant, X, kind, deadline_s, priority)

    def _query(self, tenant, X, kind, deadline_s, priority):
        payload = {"tenant": str(tenant), "kind": str(kind),
                   "x": encode_array(np.atleast_2d(
                       np.asarray(X, np.float32)))}
        if priority is not None:
            payload["priority"] = int(priority)
        budget = self.deadline_s if deadline_s is None else float(deadline_s)
        deadline_t = self._clock() + budget
        t0 = self._clock()
        self._registry.counter("fleet.front.requests").inc()
        trail: list = []
        cands = self.candidates(tenant)
        if self.hedge_after_s is None:
            out = self._sweep(tenant, payload, deadline_t, cands, trail)
        else:
            out = self._hedged(tenant, payload, deadline_t, cands, trail)
        self._registry.histogram("fleet.front.latency_s").observe(
            self._clock() - t0)
        return out

    def _hedged(self, tenant, payload, deadline_t, cands, trail):
        """Tail-tolerant variant: when the primary sweep has not
        resolved after ``hedge_after_s``, a second sweep starts on the
        rotated candidate list and the first success wins (the loser is
        abandoned, not joined — a stuck socket must not hold the
        caller)."""
        from concurrent.futures import (FIRST_COMPLETED, ThreadPoolExecutor,
                                        wait)
        if self._hedge_pool is None:
            self._hedge_pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="tdq-front-hedge")
        primary = self._hedge_pool.submit(
            self._sweep, tenant, payload, deadline_t, list(cands), trail)
        done, _ = wait({primary}, timeout=self.hedge_after_s,
                       return_when=FIRST_COMPLETED)
        if done:
            return primary.result()
        self._registry.counter("fleet.failover.hedges").inc()
        hedge_trail: list = []
        secondary = self._hedge_pool.submit(
            self._sweep, tenant, payload, deadline_t,
            list(cands[1:]) + list(cands[:1]), hedge_trail)
        futs = {primary, secondary}
        last_exc: Optional[BaseException] = None
        while futs:
            done, futs = wait(futs, return_when=FIRST_COMPLETED)
            for f in done:
                try:
                    return f.result()
                except Exception as e:
                    last_exc = e
        trail.extend(hedge_trail)
        raise last_exc if last_exc is not None \
            else ReplicaUnavailable(tenant, trail)

    # ------------------------------------------------------------------ #
    def drain(self, name: str) -> int:
        """Planned-restart drain of one replica: its in-flight waiters
        complete, then it rejects queries (failover re-homes its
        tenants) until the supervisor recycles it."""
        status, body = _http_json("POST", self.replicas[name], "/drain",
                                  payload={}, timeout=self.call_timeout_s)
        if status != 200:
            raise ReplicaRequestError(name, status,
                                      str(body.get("error")))
        return int(body.get("drained_points") or 0)

    def stats(self) -> dict:
        return {
            "replicas": {name: {"url": url,
                                "breaker": self._breakers[name].state}
                         for name, url in self.replicas.items()},
            "availability": self.availability(),
            "quorum": self.quorum,
            "degraded": self._degraded,
        }

    def autoscale_signals(self) -> dict:
        """The front tier's scale inputs: availability (the
        ``replica_availability`` SLO's gauge), quorum state, and
        per-replica breaker states — a persistently open breaker with
        availability below quorum is the 'add a replica' signal."""
        avail = self.availability()
        up = round(avail * len(self._breakers))
        return {
            "replicas": {name: b.state
                         for name, b in self._breakers.items()},
            "availability": avail,
            "quorum": self.quorum,
            "below_quorum": up < self.quorum,
            "degraded": self._degraded,
        }

    def close(self) -> None:
        if self._hedge_pool is not None:
            self._hedge_pool.shutdown(wait=False)
            self._hedge_pool = None


if __name__ == "__main__":
    main()
