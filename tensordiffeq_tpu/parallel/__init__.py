"""SPMD distribution over device meshes.

TPU-native replacement for the reference's ``tf.distribute.MirroredStrategy``
data parallelism (``models.py:235-277``, ``fit.py:150-224``): instead of
replica contexts, per-replica datasets and explicit ``strategy.reduce``
(NCCL) calls, we lay out arrays over a :class:`jax.sharding.Mesh` and let
XLA's GSPMD partitioner insert the collectives (all-reduce over ICI for the
loss/gradient means).  One program, any number of chips — the same jitted
train step runs single-chip, on a v5e-8 slice, or multi-host (DCN) after
``jax.distributed.initialize``.

Sharding layout for collocation PINNs:

* collocation points ``X_f`` — sharded along the point axis (``"data"``);
* per-point SA λ — sharded **identically to their points**, so the minimax
  ascent update is fully local (this fixes, by construction, the reference's
  broken distributed-adaptive path, ``fit.py:167``);
* network params, optimizer state, per-term scalar λ, BC meshes — replicated.

The reference's distributed path also silently disabled L-BFGS
(``fit.py:222-223``); here the L-BFGS loop is the same jitted program and
shards like everything else.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import log_event

DATA_AXIS = "data"

#: What ``compile(dist=...)`` accepts: a bool (all devices), a device count
#: (the leading ``n`` of ``jax.devices()`` — the topology-portability lever:
#: an 8-device checkpoint restores onto a ``dist=4`` solver and vice versa),
#: or an explicit device sequence.
DistSpec = Union[bool, int, Sequence]


def make_mesh(devices: Optional[Sequence] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    """1-D device mesh over all global devices — the DP topology that
    replaces ``MirroredStrategy()`` discovery (reference ``models.py:235``).
    After :func:`initialize_multihost`, ``jax.devices()`` spans every host,
    so the same call builds the pod-wide mesh."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis_name,))


def resolve_mesh(dist: DistSpec, axis_name: str = DATA_AXIS) -> Mesh:
    """Build the data-parallel mesh a ``dist=`` spec names (see
    :data:`DistSpec`).  ``dist=n`` takes the first ``n`` global devices —
    the handle the elastic-restore tests use to model an 8-device
    checkpoint resuming on a 4-device slice without a second backend."""
    if dist is True:
        return make_mesh(axis_name=axis_name)
    if isinstance(dist, bool) or dist is None:
        raise ValueError(f"dist={dist!r} names no mesh (falsy)")
    if isinstance(dist, (int, np.integer)):
        devs = jax.devices()
        if not 0 < int(dist) <= len(devs):
            raise ValueError(
                f"dist={int(dist)} devices requested but this backend has "
                f"{len(devs)}")
        return make_mesh(devs[: int(dist)], axis_name=axis_name)
    return make_mesh(list(dist), axis_name=axis_name)


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None, **kwargs):
    """Join a multi-host job (DCN-coordinated).  The reference claims
    multi-worker support but only ever builds a single-host strategy
    (``README.md:13`` vs ``models.py:235``); on TPU this is one call.

    On the **CPU backend** (tests, local clusters) cross-process
    collectives need an explicit transport — XLA's default CPU client
    rejects multi-process computations outright ("Multiprocess
    computations aren't implemented on the CPU backend", the root cause
    of the long-standing two-process tier-1 failure).  This entry point
    selects the gloo TCP transport before the backend client exists, so
    the SAME solver dist path that runs over ICI on a pod runs over
    loopback TCP in a test cluster.  Call it instead of
    ``jax.distributed.initialize`` and the platform difference disappears.
    """
    platforms = str(jax.config.jax_platforms
                    or os.environ.get("JAX_PLATFORMS", "")).lower()
    if "cpu" in platforms.split(","):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id, **kwargs)


def process_count() -> int:
    """Number of processes in the job (1 when not distributed)."""
    return jax.process_count()


def process_index() -> int:
    """This process's dense rank in ``[0, process_count())``."""
    return jax.process_index()


def is_coordinator() -> bool:
    """Is this the rank-0 process (the one that owns single-writer work:
    checkpoint meta/promotion, cluster logging)?"""
    return jax.process_index() == 0


def data_sharding(mesh: Mesh, ndim: int = 2,
                  axis_name: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (point) axis; later axes replicated."""
    return NamedSharding(mesh, P(axis_name, *(None,) * (ndim - 1)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_data_inputs(X_f, lambdas: dict, mesh: Optional[Mesh] = None):
    """Place collocation points and SA λ for data-parallel training.

    Points and any λ whose leading dimension matches the point count are
    sharded along ``"data"`` (trimming to a device-count multiple); per-term
    scalar/BC λ are replicated.  Returns the placed ``(X_f, lambdas)``.
    """
    mesh = mesh or make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    N = int(X_f.shape[0])
    N_keep = N - N % n_dev
    if N_keep != N:
        log_event("parallel", f"trimming collocation set {N} -> {N_keep} "
                  f"to tile {n_dev} devices", n_before=N, n_after=N_keep,
                  devices=n_dev)
    X_sharded = jax.device_put(X_f[:N_keep], data_sharding(mesh, X_f.ndim))

    def place(lam, per_point_ok):
        if lam is None:
            return None
        # Route structurally: only *residual* λ can be per-point (they are
        # row-aligned with X_f); BC λ always align with their face meshes and
        # must be replicated even if their length coincides with N.
        if per_point_ok and lam.ndim >= 1 and int(lam.shape[0]) == N:
            return jax.device_put(lam[:N_keep], data_sharding(mesh, lam.ndim))
        return jax.device_put(lam, replicated(mesh))

    placed = {key: [place(lam, per_point_ok=(key == "residual"))
                    for lam in terms]
              for key, terms in lambdas.items()}
    return X_sharded, placed
