"""SPMD distribution over device meshes.

TPU-native replacement for the reference's ``tf.distribute.MirroredStrategy``
data parallelism (``models.py:235-277``, ``fit.py:150-224``): instead of
replica contexts, per-replica datasets and explicit ``strategy.reduce``
(NCCL) calls, we lay out arrays over a :class:`jax.sharding.Mesh` and let
XLA's GSPMD partitioner insert the collectives (all-reduce over ICI for the
loss/gradient means).  One program, any number of chips — the same jitted
train step runs single-chip, on a v5e-8 slice, or multi-host (DCN) after
``jax.distributed.initialize``.

Sharding layout for collocation PINNs:

* collocation points ``X_f`` — sharded along the point axis (``"data"``);
* per-point SA λ — sharded **identically to their points**, so the minimax
  ascent update is fully local (this fixes, by construction, the reference's
  broken distributed-adaptive path, ``fit.py:167``);
* network params, optimizer state, per-term scalar λ, BC meshes — replicated.

The reference's distributed path also silently disabled L-BFGS
(``fit.py:222-223``); here the L-BFGS loop is the same jitted program and
shards like everything else.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import log_event

DATA_AXIS = "data"


def make_mesh(devices: Optional[Sequence] = None,
              axis_name: str = DATA_AXIS) -> Mesh:
    """1-D device mesh over all (local) devices — the DP topology that
    replaces ``MirroredStrategy()`` discovery (reference ``models.py:235``)."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis_name,))


def initialize_multihost(**kwargs):
    """Join a multi-host TPU pod job (DCN-coordinated).  The reference claims
    multi-worker support but only ever builds a single-host strategy
    (``README.md:13`` vs ``models.py:235``); on TPU this is one call."""
    jax.distributed.initialize(**kwargs)


def data_sharding(mesh: Mesh, ndim: int = 2,
                  axis_name: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (point) axis; later axes replicated."""
    return NamedSharding(mesh, P(axis_name, *(None,) * (ndim - 1)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_data_inputs(X_f, lambdas: dict, mesh: Optional[Mesh] = None):
    """Place collocation points and SA λ for data-parallel training.

    Points and any λ whose leading dimension matches the point count are
    sharded along ``"data"`` (trimming to a device-count multiple); per-term
    scalar/BC λ are replicated.  Returns the placed ``(X_f, lambdas)``.
    """
    mesh = mesh or make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    N = int(X_f.shape[0])
    N_keep = N - N % n_dev
    if N_keep != N:
        log_event("parallel", f"trimming collocation set {N} -> {N_keep} "
                  f"to tile {n_dev} devices", n_before=N, n_after=N_keep,
                  devices=n_dev)
    X_sharded = jax.device_put(X_f[:N_keep], data_sharding(mesh, X_f.ndim))

    def place(lam, per_point_ok):
        if lam is None:
            return None
        # Route structurally: only *residual* λ can be per-point (they are
        # row-aligned with X_f); BC λ always align with their face meshes and
        # must be replicated even if their length coincides with N.
        if per_point_ok and lam.ndim >= 1 and int(lam.shape[0]) == N:
            return jax.device_put(lam[:N_keep], data_sharding(mesh, lam.ndim))
        return jax.device_put(lam, replicated(mesh))

    placed = {key: [place(lam, per_point_ok=(key == "residual"))
                    for lam in terms]
              for key, terms in lambdas.items()}
    return X_sharded, placed
