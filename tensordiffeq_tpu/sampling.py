"""Collocation-point sampling.

Capability parity with the reference's vendored-SMT sampler stack
(``tensordiffeq/sampling.py``): an options-validated sampling-method hierarchy
(``sampling.py:14,148,201``) and a Latin-Hypercube sampler with the classic
criteria set including the maximin-ESE annealing optimizer
(``sampling.py:256-534``).

Fresh TPU-first implementation: plain LHS is delegated to
``scipy.stats.qmc.LatinHypercube`` (pyDOE2 is not vendored), and the
"enhanced stochastic evolutionary" (ESE) maximin optimizer is re-implemented
from the published algorithm (Jin, Chen & Sudjianto 2005) in vectorised NumPy.
Sampling is host-side setup work; determinism comes from explicit seeds
(JAX-style reproducibility) rather than global RNG state.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np
from scipy.spatial.distance import pdist
from scipy.stats import qmc


class OptionsDictionary:
    """Declare/validate options mapping (parity: reference ``sampling.py:14-146``)."""

    def __init__(self):
        self._declared: dict[str, dict[str, Any]] = {}
        self._values: dict[str, Any] = {}

    def declare(self, name: str, default: Any = None, values: Optional[Sequence] = None,
                types: Any = None, desc: str = ""):
        self._declared[name] = {"values": values, "types": types, "desc": desc}
        self._values[name] = default

    def update(self, other: dict):
        for name, value in other.items():
            self[name] = value

    def __setitem__(self, name: str, value: Any):
        if name not in self._declared:
            raise KeyError(f"Option {name!r} has not been declared")
        spec = self._declared[name]
        if spec["values"] is not None and value not in spec["values"]:
            if spec["types"] is None or not isinstance(value, spec["types"]):
                raise ValueError(
                    f"Option {name!r}: value {value!r} not in {spec['values']}")
        elif spec["types"] is not None and not isinstance(value, spec["types"]):
            raise TypeError(f"Option {name!r}: expected {spec['types']}, got {type(value)}")
        self._values[name] = value

    def __getitem__(self, name: str) -> Any:
        return self._values[name]

    def __contains__(self, name: str) -> bool:
        return name in self._values


class SamplingMethod:
    """Base sampler over a box domain (parity: reference ``sampling.py:148-198``).

    ``xlimits`` is an ``[nx, 2]`` array of per-dimension ``[lower, upper]``.
    Calling the instance with ``nt`` returns an ``[nt, nx]`` design.
    """

    def __init__(self, **kwargs):
        self.options = OptionsDictionary()
        self.options.declare("xlimits", types=np.ndarray,
                             desc="[nx, 2] per-dimension bounds")
        self._initialize()
        self.options.update(kwargs)

    def _initialize(self):
        pass

    def __call__(self, nt: int) -> np.ndarray:
        return self._compute(nt)

    def _compute(self, nt: int) -> np.ndarray:
        raise NotImplementedError


class ScaledSamplingMethod(SamplingMethod):
    """Sampler computed in the unit hypercube then affinely scaled to
    ``xlimits`` (parity: reference ``sampling.py:201-253``)."""

    def __call__(self, nt: int) -> np.ndarray:
        xlimits = self.options["xlimits"]
        unit = self._compute_unit(nt)
        return _scale_to_xlimits(unit, xlimits)

    def _compute(self, nt: int) -> np.ndarray:
        return self.__call__(nt)

    def _compute_unit(self, nt: int) -> np.ndarray:
        raise NotImplementedError


def _scale_to_xlimits(samples: np.ndarray, xlimits: np.ndarray) -> np.ndarray:
    lower = xlimits[:, 0]
    upper = xlimits[:, 1]
    return lower + samples * (upper - lower)


class LHS(ScaledSamplingMethod):
    """Latin Hypercube sampling with optimality criteria.

    Criteria (parity with reference ``sampling.py:259-311``):
      - ``'c'``/``'center'``: centered within strata
      - ``'m'``/``'maximin'``: best-of-k random designs by min pairwise distance
      - ``'cm'``/``'centermaximin'``: centered variant of maximin
      - ``'corr'``/``'correlation'``: best-of-k by minimal max off-diagonal corr
      - ``'ese'``: maximin via Enhanced Stochastic Evolutionary annealing
      - ``None``: plain randomized LHS
    """

    def _initialize(self):
        self.options.declare(
            "criterion", default="c",
            values=["center", "maximin", "centermaximin", "correlation",
                    "c", "m", "cm", "corr", "ese", None],
            desc="LHS optimality criterion")
        self.options.declare("random_state", default=None,
                             types=(int, np.random.RandomState, type(None)),
                             desc="seed or RandomState for determinism")

    def _rng(self) -> np.random.RandomState:
        rs = self.options["random_state"]
        if isinstance(rs, np.random.RandomState):
            return rs
        return np.random.RandomState(rs)

    def _compute_unit(self, nt: int) -> np.ndarray:
        xlimits = self.options["xlimits"]
        nx = xlimits.shape[0]
        crit = self.options["criterion"]
        rng = self._rng()
        seed = rng.randint(0, 2**31 - 1)

        if crit in (None, "c", "center"):
            scramble = crit is None
            sampler = qmc.LatinHypercube(d=nx, scramble=scramble, seed=seed)
            return sampler.random(nt)
        if crit in ("m", "maximin", "cm", "centermaximin"):
            scramble = crit in ("m", "maximin")
            best, best_score = None, -np.inf
            for k in range(5):
                sampler = qmc.LatinHypercube(d=nx, scramble=scramble, seed=seed + k)
                cand = sampler.random(nt)
                score = pdist(cand).min() if nt > 1 else 1.0
                if score > best_score:
                    best, best_score = cand, score
            return best
        if crit in ("corr", "correlation"):
            best, best_score = None, np.inf
            for k in range(5):
                sampler = qmc.LatinHypercube(d=nx, scramble=True, seed=seed + k)
                cand = sampler.random(nt)
                if nx < 2 or nt < 3:
                    return cand
                r = np.corrcoef(cand.T)
                score = np.max(np.abs(r - np.eye(nx)))
                if score < best_score:
                    best, best_score = cand, score
            return best
        if crit == "ese":
            sampler = qmc.LatinHypercube(d=nx, scramble=True, seed=seed)
            X0 = sampler.random(nt)
            if nt >= 3:
                from . import native
                if native.available():
                    outer, inner, J = _ese_schedule(*X0.shape)
                    return native.ese_optimize(
                        X0, outer_loops=outer, inner_loops=inner, J=J,
                        seed=seed)
            return _maximin_ese(X0, rng)
        raise ValueError(f"Unknown LHS criterion: {crit!r}")


def _ese_schedule(n: int, nx: int) -> tuple:
    """Annealing schedule (outer loops, inner loops, J proposals) shared by
    the NumPy and native C++ ESE implementations."""
    outer = min(30, max(5, int(1.5 * nx)))
    inner = min(20, max(5, n // 5))
    J = min(10, max(1, n // 10))
    return outer, inner, J


def _phi_p(X: np.ndarray, p: float = 10.0) -> float:
    """PhiP space-filling criterion (smaller = better; reference
    ``sampling.py:454-462``): ``(sum d_ij^-p)^(1/p)`` over pairwise distances."""
    d = pdist(X)
    return float((d ** (-p)).sum() ** (1.0 / p))


def _phi_p_swap(X: np.ndarray, phi: float, k: int, i1: int, i2: int,
                p: float) -> float:
    """PhiP value X would have after swapping rows ``i1``/``i2`` in column
    ``k``, computed incrementally in O(n) without modifying ``X``
    (the rank-1 update idea of reference ``sampling.py:465-513``,
    re-derived from the PhiP definition as a pure function)."""
    n = X.shape[0]
    mask = np.ones(n, dtype=bool)
    mask[[i1, i2]] = False
    others = X[mask]

    d1_old = np.sqrt(((others - X[i1]) ** 2).sum(axis=1))
    d2_old = np.sqrt(((others - X[i2]) ** 2).sum(axis=1))
    X1_new = X[i1].copy()
    X2_new = X[i2].copy()
    X1_new[k], X2_new[k] = X2_new[k], X1_new[k]
    d1_new = np.sqrt(((others - X1_new) ** 2).sum(axis=1))
    d2_new = np.sqrt(((others - X2_new) ** 2).sum(axis=1))

    res = (phi ** p
           + (d1_new ** (-p) - d1_old ** (-p)).sum()
           + (d2_new ** (-p) - d2_old ** (-p)).sum())
    return float(max(res, 0.0) ** (1.0 / p))


def _maximin_ese(X: np.ndarray, rng: np.random.RandomState, p: float = 10.0,
                 outer_loops: Optional[int] = None,
                 inner_loops: Optional[int] = None) -> np.ndarray:
    """Enhanced Stochastic Evolutionary maximin-LHS optimizer.

    Implements Jin, Chen & Sudjianto (2005) as used by the reference's
    ``_maximinESE`` / ``_ese`` (``sampling.py:315-534``): an annealing loop
    whose acceptance temperature T adapts to the accept/improve ratios, inner
    loop proposing column-wise row swaps that preserve the LHS property.
    """
    n, nx = X.shape
    if n < 3:
        return X
    default_outer, default_inner, J = _ese_schedule(n, nx)
    outer_loops = outer_loops or default_outer
    inner_loops = inner_loops or default_inner

    X = X.copy()
    phi = _phi_p(X, p)
    phi_best = phi
    X_best = X.copy()
    T = 0.005 * phi

    for _ in range(outer_loops):
        n_accept = 0
        n_improve = 0
        for inner in range(inner_loops):
            k = inner % nx
            # best of J random row-swap proposals in column k
            best_try_phi, best_pair = np.inf, None
            for _ in range(J):
                i1, i2 = rng.choice(n, size=2, replace=False)
                phi_try = _phi_p_swap(X, phi, k, i1, i2, p)
                if phi_try < best_try_phi:
                    best_try_phi, best_pair = phi_try, (i1, i2)
            i1, i2 = best_pair
            if best_try_phi - phi <= T * rng.rand():
                X[[i1, i2], k] = X[[i2, i1], k]
                phi = best_try_phi
                n_accept += 1
                if phi < phi_best:
                    phi_best = phi
                    X_best = X.copy()
                    n_improve += 1
        # temperature adaptation (Jin et al. §3.2)
        acc = n_accept / inner_loops
        imp = n_improve / inner_loops
        if imp < 0.1:
            T = T * 0.8 if acc > 0.1 else T / 0.7
        else:
            T = T * 0.9 if acc > imp else T / 0.9
    return X_best


def LatinHypercubeSample(N_f: int, bounds: np.ndarray,
                         criterion: str = "c",
                         seed: Optional[int] = None) -> np.ndarray:
    """One-call LHS over ``bounds=[nx,2]`` (parity: reference
    ``utils.py:59-61``)."""
    sampler = LHS(xlimits=np.asarray(bounds, dtype=np.float64),
                  criterion=criterion, random_state=seed)
    return sampler(N_f)
