"""Console banner & model summary at training start
(parity: reference ``tensordiffeq/output.py:5-11``, minus the pyfiglet
dependency — a static banner avoids an extra package)."""

from __future__ import annotations

import jax

_BANNER = r"""
 _____                       ___  _  __  __ ___       _____ ___ _   _
|_   _|__ _ _  ___ ___ _ _ |   \(_)/ _|/ _| __|__ _ |_   _| _ \ | | |
  | |/ -_) ' \(_-</ _ \ '_|| |) | |  _|  _| _|/ _` |  | | |  _/ |_| |
  |_|\___|_||_/__/\___/_|  |___/|_|_| |_| |___\__, |  |_| |_|  \___/
                                                 |_|
"""


def print_screen(solver, discovery_model: bool = False):
    """Print the banner, device inventory and parameter count."""
    print(_BANNER)
    devices = jax.devices()
    print(f"Backend: {devices[0].platform} | devices: {len(devices)}")
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(solver.params))
    kind = "DiscoveryModel" if discovery_model else type(solver).__name__
    print(f"{kind}: layer_sizes={getattr(solver, 'layer_sizes', '?')} "
          f"({n_params:,} parameters)")
