"""Console banner & model summary at training start
(parity: reference ``tensordiffeq/output.py:5-11``, minus the pyfiglet
dependency — a static banner avoids an extra package)."""

from __future__ import annotations

import jax

_BANNER = r"""
 _____                       ___  _  __  __ ___       _____ ___ _   _
|_   _|__ _ _  ___ ___ _ _ |   \(_)/ _|/ _| __|__ _ |_   _| _ \ | | |
  | |/ -_) ' \(_-</ _ \ '_|| |) | |  _|  _| _|/ _` |  | | |  _/ |_| |
  |_|\___|_||_/__/\___/_|  |___/|_|_| |_| |___\__, |  |_| |_|  \___/
                                                 |_|
"""


def print_screen(solver, discovery_model: bool = False):
    """Print the banner, device inventory and parameter count (and log
    the structured equivalent to any active telemetry run sink)."""
    from .telemetry import log_event
    devices = jax.devices()
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(solver.params))
    kind = "DiscoveryModel" if discovery_model else type(solver).__name__
    layer_sizes = getattr(solver, "layer_sizes", "?")
    log_event(
        "banner",
        f"{_BANNER}\nBackend: {devices[0].platform} | devices: "
        f"{len(devices)}\n{kind}: layer_sizes={layer_sizes} "
        f"({n_params:,} parameters)",
        prefix=False, backend=devices[0].platform, devices=len(devices),
        solver=kind, n_params=int(n_params))
