"""tdqlint core: one AST walk, pluggable rules, one suppression syntax.

The engine parses every source file in scope ONCE into a
:class:`ParsedModule` (AST + raw lines + ``# tdq: allow[...]``
suppressions) and hands the parsed set to each registered rule.  Rules
come in two shapes:

* **module rules** — ``check(module) -> [Finding]``, called per file the
  rule's ``files()`` filter admits;
* **project rules** — ``check_project(ctx) -> [Finding]``, called once
  with the whole :class:`Context` (cross-file properties: the metrics
  catalog diff, pallas test coverage).

Suppression syntax (the ONE escape hatch, same for every rule)::

    x = np.asarray(comps)  # tdq: allow[host-sync-in-hot-path] fenced telemetry point
    # tdq: allow[dtype-discipline] f64 row-lane packing is the multihost contract
    packed = rows.astype(np.float64)

A trailing comment covers its own line; a standalone comment line covers
the next source line.  A suppression **must** carry a reason (a finding
of rule ``suppression-missing-reason`` otherwise) and **must** match a
real finding (``unused-suppression`` otherwise) — so the allow list can
never rot into a loophole.  The two meta rules are not themselves
suppressible.

This module is deliberately **stdlib-only** (``ast``/``tokenize``/``os``/
``re``): importing it never pulls jax, so the fixture tests cost
milliseconds, not a backend init.  The jaxpr-level pass lives in
:mod:`.jaxpr_audit` and imports jax lazily.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

#: repo-relative path of the package root the default walk covers
PACKAGE_DIR = "tensordiffeq_tpu"
#: extra top-level modules in the default scope (metrics emissions ride
#: every bench payload, so bench.py is linted too)
EXTRA_FILES = ("bench.py",)

_SUPPRESS_RE = re.compile(
    r"#\s*tdq:\s*allow\[([a-z0-9-]+)\]\s*(.*?)\s*$")

#: meta rule ids the engine itself emits (never suppressible)
META_MISSING_REASON = "suppression-missing-reason"
META_UNUSED = "unused-suppression"
META_UNKNOWN_RULE = "unknown-suppression-rule"


@dataclass(frozen=True)
class Finding:
    """One ``file:line rule-id message`` report."""
    path: str          # repo-relative, forward slashes
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


@dataclass
class Suppression:
    line: int          # line the comment sits on
    target: int        # line the suppression covers
    rule: str
    reason: str
    used: bool = False


@dataclass
class ParsedModule:
    """One source file, parsed once and shared by every rule."""
    path: str          # absolute
    rel: str           # repo-relative, forward slashes
    source: str
    tree: ast.AST
    lines: list
    suppressions: list = field(default_factory=list)

    def pkg_rel(self) -> str:
        """Path relative to the package dir ('' prefix when outside)."""
        prefix = PACKAGE_DIR + "/"
        return self.rel[len(prefix):] if self.rel.startswith(prefix) else ""


def parse_suppressions(source: str, lines: list) -> list:
    """Extract ``# tdq: allow[rule] reason`` comments via tokenize (a
    string literal that *mentions* the syntax never false-positives)."""
    out = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(tok.start[0], tok.string) for tok in tokens
                    if tok.type == tokenize.COMMENT]
    except tokenize.TokenError:
        comments = []
    for lineno, text in comments:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rule, reason = m.group(1), m.group(2).strip()
        stripped = lines[lineno - 1].lstrip() if lineno <= len(lines) else ""
        if stripped.startswith("#"):
            # standalone comment: covers the next non-blank, non-comment
            # source line
            target = lineno + 1
            while target <= len(lines):
                nxt = lines[target - 1].strip()
                if nxt and not nxt.startswith("#"):
                    break
                target += 1
        else:
            target = lineno
        out.append(Suppression(lineno, target, rule, reason))
    return out


def parse_module(path: str, repo_root: str) -> ParsedModule:
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
    lines = source.splitlines()
    tree = ast.parse(source, filename=rel)
    return ParsedModule(path, rel, source, tree, lines,
                        parse_suppressions(source, lines))


@dataclass
class Context:
    """Everything a project rule may need: the parsed module set plus the
    repo root (for out-of-scope reads like docs/metrics.md)."""
    repo_root: str
    modules: list


def iter_source_files(repo_root: str):
    """Default lint scope: every ``.py`` under the package + EXTRA_FILES."""
    pkg = os.path.join(repo_root, PACKAGE_DIR)
    for root, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(root, name)
    for name in EXTRA_FILES:
        path = os.path.join(repo_root, name)
        if os.path.exists(path):
            yield path


def repo_root_default() -> str:
    """The repo this installed package lives in (…/tensordiffeq_tpu/analysis
    -> two levels up)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


class Rule:
    """Base class; subclasses set ``id``/``doc`` and override one of
    ``check`` (per module) or ``check_project`` (once)."""

    id: str = ""
    doc: str = ""

    def files(self, module: ParsedModule) -> bool:
        """Module-rule file filter; default: every file in scope (the
        package, bench.py, and any path passed explicitly to the CLI).
        Rules with a narrower contract (no-bare-print's allowlist,
        dtype-discipline's fused paths) override this."""
        return True

    def check(self, module: ParsedModule):
        return []

    def check_project(self, ctx: Context):
        return []


def run_rules(rules, repo_root=None, files=None, known_rules=None):
    """Parse once, run every rule, apply suppressions.

    Returns ``(findings, modules)`` — findings already filtered through
    the suppression pass and extended with the meta findings (missing
    reason / unused / unknown-rule suppression), sorted by path then
    line.

    ``files``: explicit file subset.  Project-scoped rules (cross-file
    properties: the metrics-catalog diff, pallas coverage) are SKIPPED
    for subset runs — judging the whole catalog against one file's
    emissions would drown a clean file in false positives.

    ``known_rules``: the full registry's rule ids; when given, a
    suppression naming an id outside it is a finding (a typo'd allow
    must not sit inert forever).
    """
    repo_root = repo_root or repo_root_default()
    subset = files is not None
    paths = list(files) if subset else list(iter_source_files(repo_root))
    modules = [parse_module(p, repo_root) for p in paths]
    ctx = Context(repo_root, modules)

    raw = []
    for rule in rules:
        for module in modules:
            if rule.files(module):
                raw.extend(rule.check(module))
        if not subset:
            raw.extend(rule.check_project(ctx))

    by_rel = {m.rel: m for m in modules}
    findings = []
    for f in raw:
        sup = None
        mod = by_rel.get(f.path)
        if mod is not None:
            for s in mod.suppressions:
                if s.target == f.line and s.rule == f.rule:
                    sup = s
                    break
        if sup is not None:
            # the suppression absorbs the finding either way; a missing
            # reason surfaces as its own meta finding below, so the run
            # still fails — but with the actionable message
            sup.used = True
            continue
        findings.append(f)
    # meta checks only judge suppressions of rules that RAN: a subset
    # run (select=...) must not read another rule's allow as stale.  A
    # suppression naming an id the full registry doesn't know is flagged
    # regardless — a typo'd allow would otherwise be silently inert.
    ran = {r.id for r in rules}
    for mod in modules:
        for s in mod.suppressions:
            if known_rules is not None and s.rule not in known_rules:
                findings.append(Finding(
                    mod.rel, s.line, META_UNKNOWN_RULE,
                    f"allow[{s.rule}] names no known rule — typo'd "
                    "suppressions never fire; known ids: "
                    + ", ".join(sorted(known_rules))))
                continue
            if s.rule not in ran:
                continue
            if not s.reason:
                findings.append(Finding(
                    mod.rel, s.line, META_MISSING_REASON,
                    f"allow[{s.rule}] carries no reason — every "
                    "suppression must say why"))
            if not s.used:
                findings.append(Finding(
                    mod.rel, s.line, META_UNUSED,
                    f"allow[{s.rule}] matches no finding on line "
                    f"{s.target} — stale suppressions must be deleted"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, modules


# --------------------------------------------------------------------- #
# shared AST helpers the rules lean on
# --------------------------------------------------------------------- #

def dotted_name(node) -> str:
    """'jax.random.split' for an Attribute/Name chain, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(node) -> str:
    """Dotted name of a Call's callee ('' when not a plain name chain)."""
    return dotted_name(node.func) if isinstance(node, ast.Call) else ""


def assigned_names(target) -> set:
    """Flat set of Names bound by an assignment target (tuples unpacked)."""
    out = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            out.add(n.id)
    return out
