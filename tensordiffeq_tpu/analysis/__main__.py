"""CLI: ``python -m tensordiffeq_tpu.analysis`` (alias ``tdqlint``).

Exit codes: 0 clean, 1 findings, 2 usage/internal error.  Output is one
``file:line rule-id message`` per finding — editor/CI friendly.
"""

import argparse
import sys

from . import ALL_RULES, run_analysis


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tdqlint",
        description="JAX-aware static analysis for tensordiffeq_tpu: "
                    "the invariants PRs 4-10 learned the hard way, as "
                    "one checked-in pass")
    ap.add_argument("files", nargs="*",
                    help="files to lint (default: the whole package "
                         "+ bench.py)")
    ap.add_argument("--select", metavar="RULES",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule ids + one-line docs and exit")
    ap.add_argument("--jaxpr", action="store_true",
                    help="also run the jaxpr-level audit over the hot-"
                         "program registry (imports jax; slower)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id:28s} {rule.doc}")
        return 0

    select = args.select.split(",") if args.select else None
    try:
        findings, _ = run_analysis(select=select,
                                   files=args.files or None)
    except ValueError as e:
        print(f"tdqlint: {e}", file=sys.stderr)
        return 2
    for f in findings:
        print(f.format())

    n_jaxpr_bad = 0
    if args.jaxpr:
        from .jaxpr_audit import audit_all
        for report in audit_all():
            status = "ok" if report.ok else "FLAGGED"
            print(f"jaxpr-audit {report.name}: {status} "
                  f"({report.summary()})")
            if not report.ok:
                n_jaxpr_bad += 1

    if findings or n_jaxpr_bad:
        total = len(findings) + n_jaxpr_bad
        print(f"tdqlint: {total} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
