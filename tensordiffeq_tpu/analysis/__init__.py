"""tdqlint — the JAX-aware static-analysis engine (PR 12).

One AST walk over the package, ~8 pluggable rules, one suppression
syntax, one CI entry point::

    python -m tensordiffeq_tpu.analysis          # AST pass, exit 1 on findings
    python -m tensordiffeq_tpu.analysis --jaxpr  # + the jaxpr-level audit
    scripts/lint.sh                              # the local alias

Each rule encodes an invariant a previous PR learned the hard way — no
host sync in the pipelined hot path (PR 10), no PRNG key reuse across
redraws (PR 10), f32-max dtype discipline in the bf16 fused paths
(PR 9), typed structured errors with the trace_id attach hook (PR 7),
donated-buffer hygiene (PR 5/9), no bare print (PR 4), metrics-catalog
drift (PR 7), and pallas interpret-mode coverage (PR 9).  See
docs/design.md for the rationale and docs/api.md for usage.

Suppress a deliberate violation with ``# tdq: allow[rule-id] reason`` —
a suppression without a reason fails, and a suppression matching no
finding fails (``unused-suppression``), so the allow list cannot rot.

This package is stdlib-only at import time: the fixture tests and the
CI gate never pay a jax import.  The jaxpr/HLO-level pass
(:mod:`.jaxpr_audit`) imports jax lazily inside its functions.
"""

from .engine import (Context, Finding, ParsedModule, Rule,  # noqa: F401
                     iter_source_files, parse_module, repo_root_default,
                     run_rules)
from .rules import ALL_RULES, RULES_BY_ID  # noqa: F401


def run_analysis(repo_root=None, select=None, files=None):
    """Run the AST pass; returns ``(findings, modules)``.

    ``select``: iterable of rule ids (default: every rule).  ``files``:
    explicit file list (default: the package + bench.py).
    """
    if select is None:
        rules = ALL_RULES
    else:
        unknown = [s for s in select if s not in RULES_BY_ID]
        if unknown:
            raise ValueError(f"unknown rule id(s): {unknown}; "
                             f"known: {sorted(RULES_BY_ID)}")
        rules = tuple(RULES_BY_ID[s] for s in select)
    return run_rules(rules, repo_root=repo_root, files=files,
                     known_rules=frozenset(RULES_BY_ID))
