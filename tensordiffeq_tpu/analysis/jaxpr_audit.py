"""The jaxpr/HLO-level audit: "zero transfers inside the step" as a
checked property, not a PERF.md claim.

The AST rules catch host syncs a human *wrote*; this pass catches the
ones a program *contains* after tracing — host callbacks
(``pure_callback``/``io_callback``/``debug_callback``/…, which lower to
``custom_call``-based host round-trips) and device→host transfers
(``device_put`` onto a host memory kind) hiding anywhere in the traced
call graph of a registered hot program, including code the AST walk
cannot see (closures built at runtime, library internals).

A small registry of hot programs is traced at micro sizes — tracing
costs milliseconds and needs no XLA compile (the same
``Lowered``-not-``compile`` trick PR 7's cost model uses):

* ``fused-minimax-step`` — the full fused SA step: loss value + weight/
  bias cotangents + the per-point ∂loss/∂w (λ-ascent direction) + the
  point cotangent (PR 9's 2.36× win; one stray ``float(tracer)`` here
  and the whole fusion falls apart).
* ``fused-minimax-system-step`` — the E-equation widening of the same
  unit (PR 16): a coupled 2-component residual with the ``[N, E]``
  per-equation weight block; systems must ride the fast path without
  re-introducing a host hop.
* ``device-resampler`` — PR 10's one-program pool→score→select redraw
  (the 163ms→1.8ms stall win is exactly "no host round-trip here").
* ``ascent-resampler`` — the PACMANN gradient-ascent redraw (PR 16):
  K clipped moves up the residual landscape + fresh replacement, one
  program; it differentiates w.r.t. the points inside the redraw, a
  natural place for a stray host fetch.
* ``serving-u`` / ``serving-residual`` — the engine's per-kind bucket
  programs (the fleet's zero-request-time-compile path).
* ``vmapped-factory-step`` — the surrogate factory's family chunk
  runner (PR 15): the minimax member loss vmapped over the model axis
  with per-member divergence masking, scanned for two steps.  "One
  program per family step" is the factory's whole throughput claim;
  a host hop here would serialize all M members on it.

jax is imported lazily inside functions: importing this module (or the
rest of :mod:`tensordiffeq_tpu.analysis`) stays stdlib-only.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

#: jaxpr primitives that round-trip through the host
HOST_CALLBACK_PRIMS = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback_call", "infeed", "outfeed",
    "debug_print",
}

#: custom_call targets in lowered StableHLO that mean a host hop
_HOST_TARGET = re.compile(
    r"callback|host|infeed|outfeed|xla_python|py_func", re.IGNORECASE)
_CUSTOM_CALL = re.compile(
    r'custom_call[^\n]*?call_target_name\s*=\s*"([^"]+)"')
_SEND_RECV = re.compile(r"stablehlo\.(send|recv)\b")


@dataclass
class AuditReport:
    """One hot program's verdict."""
    name: str
    callbacks: list = field(default_factory=list)   # jaxpr host prims
    transfers: list = field(default_factory=list)   # device->host moves
    custom_calls: list = field(default_factory=list)  # flagged HLO targets

    @property
    def ok(self) -> bool:
        return not (self.callbacks or self.transfers or self.custom_calls)

    def summary(self) -> str:
        if self.ok:
            return "0 host callbacks, 0 device->host transfers"
        parts = []
        if self.callbacks:
            parts.append(f"host callbacks: {sorted(set(self.callbacks))}")
        if self.transfers:
            parts.append(f"transfers: {sorted(set(self.transfers))}")
        if self.custom_calls:
            parts.append(
                f"host custom_calls: {sorted(set(self.custom_calls))}")
        return "; ".join(parts)


def _scan_jaxpr(jaxpr, report: AuditReport) -> None:
    """Recursively collect host-hop primitives from a jaxpr (descending
    into every sub-jaxpr carried in eqn params: scan/cond/pjit bodies,
    custom_vjp branches, …)."""
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in HOST_CALLBACK_PRIMS:
            report.callbacks.append(prim)
        elif prim == "device_put":
            # flag only host-bound placements: a sharding constraint or
            # device->device move is legal inside a step
            for dst in (eqn.params.get("devices") or []):
                kind = getattr(dst, "memory_kind", None)
                if kind is not None and "host" in str(kind):
                    report.transfers.append(f"device_put->{kind}")
        for val in eqn.params.values():
            for sub in _sub_jaxprs(val):
                _scan_jaxpr(sub, report)


def _sub_jaxprs(val):
    """Jaxprs carried in an eqn param — duck-typed (Jaxpr has ``eqns``,
    ClosedJaxpr wraps one in ``.jaxpr``) so no private jax imports."""
    if hasattr(val, "eqns"):
        yield val
    elif hasattr(val, "jaxpr") and hasattr(val.jaxpr, "eqns"):
        yield val.jaxpr
    elif isinstance(val, (tuple, list)):
        for v in val:
            yield from _sub_jaxprs(v)


def _scan_stablehlo(text: str, report: AuditReport) -> None:
    for m in _CUSTOM_CALL.finditer(text):
        target = m.group(1)
        if _HOST_TARGET.search(target):
            report.custom_calls.append(target)
    for m in _SEND_RECV.finditer(text):
        report.transfers.append(f"stablehlo.{m.group(1)}")


# --------------------------------------------------------------------- #
# the hot-program registry (micro sizes: tracing only, no compile)
# --------------------------------------------------------------------- #

def _micro_net(seed=0, widths=(8, 8), n_out=1):
    import jax
    import jax.numpy as jnp

    from ..networks import neural_net
    net = neural_net([2, *widths, n_out])
    params = net.init(jax.random.PRNGKey(seed), jnp.zeros((1, 2)))
    return net, params


def _minimax_program():
    """The fused SA minimax step: value + every cotangent it emits."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.derivatives import grad
    from ..ops.fused import analyze_f_model
    from ..ops.pallas_minimax import build_minimax_sq_fn
    from ..ops.taylor import extract_mlp_layers

    net, params = _micro_net()
    layers = extract_mlp_layers(params)
    shapes = [(W.shape[0], W.shape[1]) for W, _ in layers]

    def f_model(u, x, t):  # AC-type: primal + u_t + u_xx
        return (grad(u, "t")(x, t) - 0.05 * grad(grad(u, "x"), "x")(x, t)
                + u(x, t) ** 3 - u(x, t))

    reqs = analyze_f_model(f_model, ("x", "t"), 1)
    sq = build_minimax_sq_fn(f_model, ("x", "t"), 1, reqs, shapes)
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randn(16, 2) * 0.5, jnp.float32)
    w = jnp.asarray(rng.rand(16, 1), jnp.float32)

    def step(layers, w, X):
        val, vjp = jax.vjp(sq, layers, w, X)
        g_layers, g_w, g_X = vjp(jnp.ones((), val.dtype))
        return val, g_layers, g_w, g_X

    return step, (layers, w, X)


def _minimax_system_program():
    """The E-equation widened fused step (PR 16): a coupled 2-component
    f_model through the same value-plus-every-cotangent unit, with the
    ``[N, E]`` per-equation weight block.  The widening must not cost the
    fusion its host-hop-free property — the whole point of lifting
    systems onto the fast path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.derivatives import grad
    from ..ops.fused import analyze_f_model
    from ..ops.pallas_minimax import build_minimax_sq_fn
    from ..ops.taylor import extract_mlp_layers

    net, params = _micro_net(seed=4, n_out=2)
    layers = extract_mlp_layers(params)
    shapes = [(W.shape[0], W.shape[1]) for W, _ in layers]

    def f_model(u, x, t):  # Schrödinger-type coupled pair
        f_u = grad(u[0], "t")(x, t) + 0.5 * grad(grad(u[1], "x"), "x")(x, t)
        f_v = grad(u[1], "t")(x, t) - 0.5 * grad(grad(u[0], "x"), "x")(x, t)
        return f_u, f_v

    reqs = analyze_f_model(f_model, ("x", "t"), 2)
    sq = build_minimax_sq_fn(f_model, ("x", "t"), 2, reqs, shapes)
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randn(16, 2) * 0.5, jnp.float32)
    w = jnp.asarray(rng.rand(16, 2), jnp.float32)

    def step(layers, w, X):
        val, vjp = jax.vjp(sq, layers, w, X)
        g_layers, g_w, g_X = vjp(jnp.ones((), val.dtype))
        return val, g_layers, g_w, g_X

    return step, (layers, w, X)


def _resampler_program():
    """PR 10's one-program pool->score->select redraw."""
    import jax.numpy as jnp
    import numpy as np

    from ..ops.resampling import DeviceResampler

    net, params = _micro_net(seed=1)

    def residual_fn(params, X):
        return net.apply(params, X)

    xlimits = np.array([[-1.0, 1.0], [0.0, 1.0]])
    r = DeviceResampler(residual_fn, xlimits, n_f=16, pool_factor=2)
    X = jnp.zeros((16, 2), jnp.float32)
    return r._redraw_impl, (params, X, jnp.asarray(0))


def _ascent_resampler_program():
    """The PACMANN ascent redraw (PR 16): K clipped gradient-ascent
    moves + lowest-score fresh replacement as one program.  The mover
    differentiates the residual w.r.t. the POINTS inside the redraw — a
    natural place for a stray host fetch to creep in."""
    import jax.numpy as jnp
    import numpy as np

    from ..ops.resampling import AscentResampler

    net, params = _micro_net(seed=5)

    def residual_fn(params, X):
        return net.apply(params, X)

    xlimits = np.array([[-1.0, 1.0], [0.0, 1.0]])
    r = AscentResampler(residual_fn, xlimits, n_f=16, n_steps=2,
                        fresh_frac=0.25)
    X = jnp.zeros((16, 2), jnp.float32)
    return r._redraw_impl, (params, X, jnp.asarray(0))


def _serving_program(kind: str):
    """The engine's per-kind bucket program (what each rung jits)."""
    import jax.numpy as jnp

    from ..ops.derivatives import grad
    from ..serving.surrogate import Surrogate

    def builder():
        net, params = _micro_net(seed=2)

        def f_model(u, x, t):
            return grad(u, "t")(x, t) + u(x, t) * grad(u, "x")(x, t)

        sur = Surrogate(net, params, ("x", "t"), f_model=f_model)
        eng = sur.engine(min_bucket=32)
        batched = eng.make_batched(kind)()
        X = jnp.zeros((32, 2), jnp.float32)
        return batched, (params, X)
    return builder


def _factory_program():
    """The surrogate factory's vmapped family step (2 members, 2 scanned
    optimizer steps, minimax member loss with a traced θ and the
    per-member divergence mask) — built WITHOUT a template solver so the
    trace stays compile-free."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ..factory.family import make_family_runner, stack_members
    from ..ops.derivatives import grad
    from ..ops.fused import analyze_f_model
    from ..ops.pallas_minimax import (build_minimax_sq_fn,
                                      make_minimax_residual_loss)
    from ..training.fit import make_optimizer

    def f_model(u, x, t, th):  # AC-type with the family coefficient θ
        return (grad(u, "t")(x, t)
                - th * grad(grad(u, "x"), "x")(x, t)
                + u(x, t) ** 3 - u(x, t))

    net, _ = _micro_net(seed=3)
    reqs = analyze_f_model(lambda u, x, t: f_model(u, x, t, 0.05),
                           ("x", "t"), 1)
    shapes = [(2, 8), (8, 8), (8, 1)]
    M, N = 2, 16

    def member_vg(tr_m, X_m, theta):
        def lo(tr):
            sq = build_minimax_sq_fn(
                lambda u, x, t: f_model(u, x, t, theta),
                ("x", "t"), 1, reqs, shapes, use_pallas=False)
            mm = make_minimax_residual_loss(sq)
            total = mm(tr["params"], tr["lambdas"]["residual"], X_m)
            return total, {"Total Loss": total}
        (total, comps), grads = jax.value_and_grad(
            lo, has_aux=True)(tr_m)
        return total, comps, grads, optax.global_norm(grads)

    opt = make_optimizer()
    params = stack_members(
        [net.init(jax.random.PRNGKey(m), jnp.zeros((1, 2)))
         for m in range(M)])
    trainables = {"params": params,
                  "lambdas": {"residual": [jnp.ones((M, N, 1))],
                              "BCs": []}}
    opt_state = opt.init(trainables)
    alive = jnp.ones((M,), bool)
    best = (jax.tree_util.tree_map(jnp.array, params),
            jnp.full((M,), jnp.inf), jnp.full((M,), -1, jnp.int32))
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randn(M, N, 2) * 0.5, jnp.float32)
    thetas = jnp.asarray([0.01, 0.05], jnp.float32)
    run = make_family_runner(member_vg, opt, M)

    def step(trainables, opt_state, alive, best, X, thetas):
        return run(trainables, opt_state, alive, best, X, thetas,
                   jnp.asarray(0), 2)

    return step, (trainables, opt_state, alive, best, X, thetas)


HOT_PROGRAMS = {
    "fused-minimax-step": _minimax_program,
    "fused-minimax-system-step": _minimax_system_program,
    "device-resampler": _resampler_program,
    "ascent-resampler": _ascent_resampler_program,
    "serving-u": _serving_program("u"),
    "serving-residual": _serving_program("residual"),
    "vmapped-factory-step": _factory_program,
}


def audit(name: str) -> AuditReport:
    """Trace + lower one registered hot program and scan for host hops.

    Trace-level (``make_jaxpr``) catches callback/transfer *primitives*;
    lowering to StableHLO text (``Lowered.as_text`` — still no XLA
    compile) catches ``custom_call``-based host hooks the primitives
    lower into.  Both must be clean."""
    import jax

    fn, args = HOT_PROGRAMS[name]()
    report = AuditReport(name)
    closed = jax.make_jaxpr(fn)(*args)
    _scan_jaxpr(closed.jaxpr, report)
    _scan_stablehlo(jax.jit(fn).lower(*args).as_text(), report)
    return report


def audit_all():
    return [audit(name) for name in HOT_PROGRAMS]
