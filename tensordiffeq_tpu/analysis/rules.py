"""The tdqlint rule set — every invariant the last ten PRs learned the
hard way, as one checked-in analysis pass (see docs/design.md for the
PR-by-PR rationale).

Rules are heuristics, not proofs: they under-report (no interprocedural
analysis) and occasionally flag a deliberate site — that is what the
``# tdq: allow[rule-id] reason`` escape hatch is for.  Every rule here is
pure-AST (stdlib only); the jaxpr-level pass lives in
:mod:`.jaxpr_audit`.
"""

from __future__ import annotations

import ast
import os
import re

from .engine import (Context, Finding, ParsedModule, Rule, assigned_names,
                     call_name, dotted_name)

# --------------------------------------------------------------------- #
# shared helpers
# --------------------------------------------------------------------- #

_JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.pjit"}
_SCAN_NAMES = {"jax.lax.scan", "lax.scan"}


def _is_jit_decorator(dec) -> bool:
    """True for ``@jit`` / ``@jax.jit`` / ``@partial(jax.jit, ...)`` /
    ``@jax.jit(...)`` decorator nodes."""
    name = dotted_name(dec)
    if name in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        cname = call_name(dec)
        if cname in _JIT_NAMES:
            return True
        if cname in ("partial", "functools.partial") and dec.args:
            return dotted_name(dec.args[0]) in _JIT_NAMES
    return False


def _walk_in_order(node, skip_defs=True):
    """Yield descendants in source order; optionally do not descend into
    nested function/class definitions (they are their own scope)."""
    for child in ast.iter_child_nodes(node):
        yield child
        if skip_defs and isinstance(child, (ast.FunctionDef,
                                            ast.AsyncFunctionDef,
                                            ast.ClassDef, ast.Lambda)):
            continue
        yield from _walk_in_order(child, skip_defs)


def _function_defs(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# --------------------------------------------------------------------- #
# 1 · host-sync-in-hot-path
# --------------------------------------------------------------------- #

class HostSyncRule(Rule):
    """No host synchronisation inside the hot path.

    PR 10 measured the cost of one stray sync: 163 ms of host stall per
    redraw, 1.8 ms once removed.  Hot contexts are (a) jit-decorated or
    ``jax.jit(fn)``-wrapped functions and their nested bodies, (b)
    ``lax.scan`` body functions, (c) the fit chunk-loop drivers
    (``fit_adam`` / ``lbfgs_minimize``) — where only *transfer-class*
    syncs are flagged (``block_until_ready``, ``np.asarray``/``np.array``),
    since scalar ``float()`` on already-transferred host data is free.
    Deliberate fenced telemetry points carry an allow with the reason.
    """

    id = "host-sync-in-hot-path"
    doc = "no .block_until_ready/np.asarray/float()/.item() in jit, " \
          "scan bodies, or the fit chunk loops"

    CHUNK_RUNNERS = {"fit_adam", "lbfgs_minimize"}
    NP_TRANSFER = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                   "onp.asarray", "onp.array"}
    TRACED_ONLY_ATTRS = {"item", "tolist"}

    def _hot_defs(self, module: ParsedModule):
        """(def_node, traced) pairs: traced=True for jit/scan contexts,
        False for the chunk-loop drivers."""
        jit_wrapped, scan_bodies = set(), set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                cname = call_name(node)
                if cname in _JIT_NAMES and node.args:
                    target = node.args[0]
                    if isinstance(target, ast.Name):
                        jit_wrapped.add(target.id)
                    elif isinstance(target, ast.Attribute):
                        jit_wrapped.add(target.attr)
                elif cname in _SCAN_NAMES and node.args \
                        and isinstance(node.args[0], ast.Name):
                    scan_bodies.add(node.args[0].id)
        for fn in _function_defs(module.tree):
            if any(_is_jit_decorator(d) for d in fn.decorator_list) \
                    or fn.name in jit_wrapped or fn.name in scan_bodies:
                yield fn, True
            elif fn.name in self.CHUNK_RUNNERS:
                yield fn, False

    def check(self, module: ParsedModule):
        findings, seen = [], set()
        for fn, traced in self._hot_defs(module):
            ctx = "traced context" if traced else "fit chunk loop"
            for node in ast.walk(fn):
                hit = None
                if isinstance(node, ast.Attribute):
                    name = dotted_name(node)
                    if node.attr == "block_until_ready":
                        hit = ".block_until_ready() host fence"
                    elif name in self.NP_TRANSFER:
                        hit = f"{name} device->host transfer"
                elif traced and isinstance(node, ast.Call):
                    if isinstance(node.func, ast.Name) \
                            and node.func.id == "float" and node.args:
                        hit = "float() forces a host sync on a traced value"
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr in self.TRACED_ONLY_ATTRS:
                        hit = f".{node.func.attr}() forces a host sync"
                    elif call_name(node) == "jax.device_get":
                        hit = "jax.device_get host transfer"
                if hit and (node.lineno, hit) not in seen:
                    seen.add((node.lineno, hit))
                    findings.append(Finding(
                        module.rel, node.lineno, self.id,
                        f"{hit} inside {ctx} '{fn.name}' — hot-path "
                        "host syncs serialize the device (PR 10: "
                        "163ms->1.8ms per redraw)"))
        return findings


# --------------------------------------------------------------------- #
# 2 · prng-key-reuse
# --------------------------------------------------------------------- #

class PrngKeyReuseRule(Rule):
    """A PRNG key consumed twice without ``split``/``fold_in`` between
    uses produces correlated draws — exactly the bug the device
    resampler's ``fold_in(seed, epoch)`` discipline exists to prevent
    (PR 10: a reused key across redraws silently re-selects the same
    points and the adaptive win evaporates)."""

    id = "prng-key-reuse"
    doc = "no jax.random call re-consuming a key without split/fold_in"

    NONCONSUMING = {"split", "fold_in", "PRNGKey", "key", "key_data",
                    "wrap_key_data", "clone"}

    def _random_aliases(self, module: ParsedModule):
        """(prefixes, bare) — dotted prefixes that mean jax.random, and
        bare names imported from it."""
        prefixes, bare = {"jax.random"}, {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax.random":
                        prefixes.add(a.asname or "jax.random")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "random":
                            prefixes.add(a.asname or "random")
                elif node.module == "jax.random":
                    for a in node.names:
                        bare[a.asname or a.name] = a.name
        return prefixes, bare

    def _consuming_call(self, node, prefixes, bare):
        """The consumed key Name id, or None."""
        if not isinstance(node, ast.Call) or not node.args:
            return None
        fn = None
        name = call_name(node)
        if name in bare:
            fn = bare[name]
        else:
            head, _, tail = name.rpartition(".")
            if head in prefixes:
                fn = tail
        if fn is None or fn in self.NONCONSUMING:
            return None
        first = node.args[0]
        return first.id if isinstance(first, ast.Name) else None

    def check(self, module: ParsedModule):
        findings = []
        prefixes, bare = self._random_aliases(module)
        scopes = [module.tree] + list(_function_defs(module.tree))
        for scope in scopes:
            consumed = {}
            for node in _walk_in_order(scope):
                key = self._consuming_call(node, prefixes, bare)
                if key is not None:
                    if key in consumed:
                        findings.append(Finding(
                            module.rel, node.lineno, self.id,
                            f"PRNG key '{key}' already consumed at line "
                            f"{consumed[key]} — split or fold_in before "
                            "reuse (reused keys correlate draws)"))
                    else:
                        consumed[key] = node.lineno
                elif isinstance(node, (ast.Assign, ast.AugAssign,
                                       ast.AnnAssign, ast.For)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [getattr(node, "target", None)]
                    for t in targets:
                        if t is not None:
                            for nm in assigned_names(t):
                                consumed.pop(nm, None)
        return findings


# --------------------------------------------------------------------- #
# 3 · dtype-discipline
# --------------------------------------------------------------------- #

class DtypeDisciplineRule(Rule):
    """The bf16 fused paths (``ops/``, ``serving/engine.py``) must not
    smuggle float64 in: one f64 leaf re-promotes whole XLA fusions and
    silently halves the measured bf16 throughput (PR 9's end-to-end bf16
    work).  Host-side f64 selection math is legal but must say so with an
    allow."""

    id = "dtype-discipline"
    doc = "no float64 dtypes inside the bf16 fused paths " \
          "(ops/, serving/engine.py)"

    F64_ATTRS = {"np.float64", "numpy.float64", "jnp.float64",
                 "jax.numpy.float64"}

    def files(self, module: ParsedModule) -> bool:
        return (module.rel.startswith("tensordiffeq_tpu/ops/")
                or module.rel == "tensordiffeq_tpu/serving/engine.py")

    def check(self, module: ParsedModule):
        findings, seen = [], set()
        for node in ast.walk(module.tree):
            hit = None
            if isinstance(node, ast.Attribute) \
                    and dotted_name(node) in self.F64_ATTRS:
                hit = dotted_name(node)
            elif isinstance(node, ast.Constant) and node.value == "float64":
                hit = '"float64"'
            if hit and node.lineno not in seen:
                seen.add(node.lineno)
                findings.append(Finding(
                    module.rel, node.lineno, self.id,
                    f"{hit} inside a bf16 fused path — f64 leaves "
                    "re-promote XLA fusions; keep device math <= f32 or "
                    "allow with the host-side reason"))
        return findings


# --------------------------------------------------------------------- #
# 4 · bare-raise-discipline
# --------------------------------------------------------------------- #

class RaiseDisciplineRule(Rule):
    """Every raise uses a *typed* error, and every public error class
    declares the ``trace_id`` attach hook (PR 7: structured errors carry
    the trace id that resolves the failure's span tree in the run log —
    a generic ``RuntimeError`` is invisible to that machinery)."""

    id = "bare-raise-discipline"
    doc = "no generic RuntimeError/Exception raises; public error " \
          "classes declare trace_id"

    GENERIC = {"Exception", "RuntimeError", "BaseException"}
    BUILTIN_BASES = {"Exception", "BaseException", "RuntimeError",
                     "ValueError", "TypeError", "KeyError", "OSError",
                     "ArithmeticError", "LookupError", "IOError"}

    def _error_classes(self, ctx: Context):
        """{name: (module, node, has_trace_id, base_names)} over the
        package, closed transitively over package bases."""
        classes = {}
        for module in ctx.modules:
            if not module.rel.startswith("tensordiffeq_tpu/"):
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                bases = {dotted_name(b).rpartition(".")[2]
                         for b in node.bases}
                # the hook is a class attr (`trace_id = None`), an
                # annotated one, or an instance attr set in __init__
                # (`self.trace_id = ...`, RequestTimeout-style)
                has_tid = False
                for n in ast.walk(node):
                    if isinstance(n, ast.Assign):
                        for t in n.targets:
                            if (isinstance(t, ast.Name)
                                    and t.id == "trace_id") \
                                or (isinstance(t, ast.Attribute)
                                    and t.attr == "trace_id"
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                has_tid = True
                    elif isinstance(n, ast.AnnAssign) \
                            and isinstance(n.target, ast.Name) \
                            and n.target.id == "trace_id":
                        has_tid = True
                classes[node.name] = (module, node, has_tid, bases)
        # keep only exception classes: a base is a builtin exception or
        # another collected error class (iterate to fixpoint)
        errors = {}
        changed = True
        while changed:
            changed = False
            for name, (module, node, has_tid, bases) in classes.items():
                if name in errors:
                    continue
                if bases & self.BUILTIN_BASES or bases & errors.keys():
                    errors[name] = (module, node, has_tid, bases)
                    changed = True

        def carries_trace_id(name, seen=()):
            if name not in errors or name in seen:
                return False
            module, node, has_tid, bases = errors[name]
            return has_tid or any(carries_trace_id(b, seen + (name,))
                                  for b in bases)

        return errors, carries_trace_id

    def check_project(self, ctx: Context):
        findings = []
        errors, carries_trace_id = self._error_classes(ctx)
        for name, (module, node, _tid, _bases) in errors.items():
            if name.startswith("_"):
                continue  # private control-flow sentinels are exempt
            if not carries_trace_id(name):
                findings.append(Finding(
                    module.rel, node.lineno, self.id,
                    f"error class {name} does not declare the trace_id "
                    "attach hook (add `trace_id = None` so attach_trace "
                    "resolves failures to their span tree)"))
        for module in ctx.modules:
            if not module.rel.startswith("tensordiffeq_tpu/"):
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = node.exc
                name = call_name(exc) if isinstance(exc, ast.Call) \
                    else dotted_name(exc)
                if name.rpartition(".")[2] in self.GENERIC:
                    findings.append(Finding(
                        module.rel, node.lineno, self.id,
                        f"generic `raise {name}` — use a typed error "
                        "from the structured set so callers and the "
                        "trace layer can dispatch on it"))
        return findings


# --------------------------------------------------------------------- #
# 5 · donated-buffer-reuse
# --------------------------------------------------------------------- #

class DonatedBufferReuseRule(Rule):
    """An argument donated to a jitted program is deleted by the call —
    touching it afterwards reads a dead buffer (an opaque XLA error at
    best, silent garbage under some backends).  The chunk runners donate
    their carried state (PR 5/9), so every call site must rebind the
    donated names at the call."""

    id = "donated-buffer-reuse"
    doc = "no use of a variable after it was passed in a donated " \
          "argument position"

    def _donating(self, module: ParsedModule):
        """{callable_name: donated positions} for jit-with-donate defs
        and ``f = jax.jit(g, donate_argnums=...)`` assignments."""
        out = {}

        def positions(call):
            for kw in call.keywords:
                if kw.arg in ("donate_argnums", "donate_argnames") \
                        and isinstance(kw.value, (ast.Tuple, ast.List)):
                    return tuple(e.value for e in kw.value.elts
                                 if isinstance(e, ast.Constant)
                                 and isinstance(e.value, int))
                if kw.arg == "donate_argnums" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, int):
                    return (kw.value.value,)
            return ()

        for fn in _function_defs(module.tree):
            for dec in fn.decorator_list:
                if isinstance(dec, ast.Call) and _is_jit_decorator(dec):
                    pos = positions(dec)
                    if pos:
                        out[fn.name] = pos
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and call_name(node.value) in _JIT_NAMES:
                pos = positions(node.value)
                if pos:
                    for t in node.targets:
                        for nm in assigned_names(t):
                            out[nm] = pos
        return out

    @staticmethod
    def _innermost_stmt(stmts, call):
        """Index of the innermost statement whose subtree contains
        ``call`` — a call inside an Assign inside a While must attribute
        to the Assign, whose targets rebind the donated names.  The
        source-order list puts the innermost container last."""
        best = None
        for i, stmt in enumerate(stmts):
            if any(n is call for n in ast.walk(stmt)):
                best = i
        return best

    def check(self, module: ParsedModule):
        donating = self._donating(module)
        if not donating:
            return []
        findings = []
        for fn in _function_defs(module.tree):
            stmts = [n for n in _walk_in_order(fn)
                     if isinstance(n, ast.stmt)]
            calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)
                     and call_name(n).rpartition(".")[2] in donating]
            for call in calls:
                i = self._innermost_stmt(stmts, call)
                if i is None:
                    continue
                stmt = stmts[i]
                cname = call_name(call).rpartition(".")[2]
                rebound = set()
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        rebound |= assigned_names(t)
                for p in donating[cname]:
                    if p >= len(call.args) \
                            or not isinstance(call.args[p], ast.Name):
                        continue
                    var = call.args[p].id
                    if var in rebound:
                        continue  # the donation idiom: rebind at the call
                    for later in stmts[i + 1:]:
                        loads = {n.id for n in ast.walk(later)
                                 if isinstance(n, ast.Name)
                                 and isinstance(n.ctx, ast.Load)}
                        if var in loads:
                            findings.append(Finding(
                                module.rel, later.lineno, self.id,
                                f"'{var}' was donated to {cname}() at "
                                f"line {call.lineno} and is referenced "
                                "afterwards — donated buffers are "
                                "deleted by the call"))
                            break
                        later_binds = set()
                        for n in ast.walk(later):
                            if isinstance(n, (ast.Assign, ast.For)):
                                tgts = n.targets if isinstance(
                                    n, ast.Assign) else [n.target]
                                for t in tgts:
                                    later_binds |= assigned_names(t)
                        if var in later_binds:
                            break
        return findings


# --------------------------------------------------------------------- #
# 6 · no-bare-print
# --------------------------------------------------------------------- #

class NoBarePrintRule(Rule):
    """All package narration routes through ``telemetry.log_event``
    (leveled, honours ``verbose``, mirrored into the JSONL sink) so quiet
    runs are quiet and events are machine-readable (PR 4).  Only the
    telemetry package itself, the progress bar, and the lint CLI module
    (whose stdout IS its product — the engine/rules/audit modules stay
    guarded) may print."""

    id = "no-bare-print"
    doc = "no bare print() outside telemetry/, training/progress.py, " \
          "and the lint CLI module"

    ALLOWED_PREFIXES = ("telemetry/",)
    ALLOWED_FILES = ("training/progress.py", "analysis/__main__.py")

    def files(self, module: ParsedModule) -> bool:
        rel = module.pkg_rel()
        if not rel:
            return False
        return not (rel.startswith(self.ALLOWED_PREFIXES)
                    or rel in self.ALLOWED_FILES)

    def check(self, module: ParsedModule):
        return [Finding(module.rel, node.lineno, self.id,
                        "bare print() — route narration through "
                        "telemetry.log_event so quiet runs stay quiet "
                        "and events reach the JSONL sink")
                for node in ast.walk(module.tree)
                if isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"]


# --------------------------------------------------------------------- #
# 7 · metrics-catalog
# --------------------------------------------------------------------- #

#: pre-PR-7 names wired into the bench payload contract; the catalog's
#: legacy section documents them.  Frozen: new metrics must be dotted.
LEGACY_METRICS = {"step_time_dispatch_s", "step_time_device_s",
                  "step_time_data_s", "checkpoints", "divergences",
                  "device_memory_peak_bytes"}

_DOTTED = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
_EMITTERS = {"counter", "gauge", "histogram"}
_CATALOG_ROW = re.compile(r"^\s*\|\s*`([a-z0-9_.]+)`\s*\|")
CATALOG_PATH = os.path.join("docs", "metrics.md")


def emitted_metrics(ctx: Context) -> dict:
    """``{name: [(rel, line), ...]}`` over the package + bench.py —
    an emission is ``<expr>.counter("lit", ...)`` (/gauge/histogram) with
    a string-literal first argument; ``IfExp`` first args count both
    arms.  ``telemetry/registry.py`` (the instrument definitions) is
    excluded."""
    out = {}
    for module in ctx.modules:
        if module.rel == "tensordiffeq_tpu/telemetry/registry.py":
            continue
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EMITTERS and node.args):
                continue
            arg = node.args[0]
            names = []
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                names.append(arg.value)
            elif isinstance(arg, ast.IfExp):
                for side in (arg.body, arg.orelse):
                    if isinstance(side, ast.Constant) \
                            and isinstance(side.value, str):
                        names.append(side.value)
            for name in names:
                out.setdefault(name, []).append((module.rel, node.lineno))
    return out


def catalog_metrics(repo_root: str) -> dict:
    """``{name: line}`` — the backticked first cell of each table row in
    docs/metrics.md."""
    names = {}
    with open(os.path.join(repo_root, CATALOG_PATH)) as fh:
        for lineno, line in enumerate(fh, 1):
            m = _CATALOG_ROW.match(line)
            if m:
                names.setdefault(m.group(1), lineno)
    return names


class MetricsCatalogRule(Rule):
    """docs/metrics.md is the operator contract for every emitted
    instrument (PR 7): emissions missing from the catalog, stale catalog
    rows, names violating the dotted ``subsystem.noun[.verb]`` scheme,
    and legacy-allowlist entries whose emission is gone are all drift."""

    id = "metrics-catalog"
    doc = "every metric emission catalogued in docs/metrics.md, " \
          "dotted naming, no stale rows"

    def __init__(self, legacy=frozenset(LEGACY_METRICS)):
        self.legacy = frozenset(legacy)

    def check_project(self, ctx: Context):
        findings = []
        emitted = emitted_metrics(ctx)
        catalog = catalog_metrics(ctx.repo_root)
        for name, sites in sorted(emitted.items()):
            if name not in catalog:
                rel, line = sites[0]
                findings.append(Finding(
                    rel, line, self.id,
                    f"metric '{name}' is emitted but missing from "
                    f"{CATALOG_PATH} — document it or rename"))
            if name not in self.legacy and not _DOTTED.match(name):
                rel, line = sites[0]
                findings.append(Finding(
                    rel, line, self.id,
                    f"metric '{name}' violates the dotted "
                    "subsystem.noun[.verb] scheme (the legacy allowlist "
                    "is frozen)"))
        for name, line in sorted(catalog.items()):
            if name not in emitted:
                findings.append(Finding(
                    CATALOG_PATH, line, self.id,
                    f"catalog row '{name}' has no emission in the "
                    "source — remove the row or restore the emission"))
        for name in sorted(self.legacy - emitted.keys()):
            findings.append(Finding(
                CATALOG_PATH, catalog.get(name, 1), self.id,
                f"legacy allowlist entry '{name}' is no longer emitted "
                "— delete it from the allowlist and the catalog"))
        return findings


# --------------------------------------------------------------------- #
# 8 · pallas-interpret-coverage
# --------------------------------------------------------------------- #

class PallasCoverageRule(Rule):
    """Every ``ops/`` module that launches a pallas kernel must be
    exercised by an interpret-mode CPU test in tests/test_pallas.py —
    interpret mode is the only pre-hardware signal tier-1 has (it
    already missed three Mosaic-only failures once, PERF.md)."""

    id = "pallas-interpret-coverage"
    doc = "every ops/ pallas_call covered by an interpret-mode test " \
          "in tests/test_pallas.py"

    TEST_FILE = os.path.join("tests", "test_pallas.py")
    _PALLAS_CALL = re.compile(r"\bpallas_call\s*\(")

    def check_project(self, ctx: Context):
        findings = []
        test_path = os.path.join(ctx.repo_root, self.TEST_FILE)
        try:
            with open(test_path) as fh:
                test_src = fh.read()
        except OSError:
            test_src = ""
        has_interpret = "interpret=True" in test_src
        for module in ctx.modules:
            if not module.rel.startswith("tensordiffeq_tpu/ops/"):
                continue
            m = self._PALLAS_CALL.search(module.source)
            if not m:
                continue
            mod = os.path.basename(module.rel)[:-3]
            line = module.source[:m.start()].count("\n") + 1
            if f"ops.{mod} import" not in test_src or not has_interpret:
                findings.append(Finding(
                    module.rel, line, self.id,
                    f"ops module '{mod}' launches a pallas kernel but "
                    f"registers no interpret-mode test in "
                    f"{self.TEST_FILE} — interpret mode is the only "
                    "pre-hardware signal tier-1 has"))
        return findings


# --------------------------------------------------------------------- #
# 9 · span-leak
# --------------------------------------------------------------------- #

class SpanLeakRule(Rule):
    """A span that is opened and never closed never emits its ``trace``
    record: the run log shows the parent finishing instantly, the
    Perfetto export drops the slice, and every child becomes an orphan
    root in ``span_tree`` (PR 19 — the flight recorder's "final span"
    narration is only trustworthy if spans reliably close).

    Two shapes are flagged: ``tracer.span(...)`` whose result is not
    entered with ``with`` (the context manager never runs, so the span
    never even opens), and ``open_span(...)`` whose result is discarded
    or bound to a name that is neither ``close_span``'d nor escapes the
    scope.  Escapes — passed as a call argument, returned, yielded,
    stored to an attribute, aliased — count as closed (no
    interprocedural analysis; under-report by design).
    """

    id = "span-leak"
    doc = "tracer.span() entered via with; open_span() results " \
          "close_span'd or escaping the scope"

    #: the tracer implementation itself builds/returns spans freely
    SKIP = ("tensordiffeq_tpu/telemetry/tracing.py",)
    #: receiver-name filter for bare ``.span`` (``re.Match.span()`` and
    #: friends must not trip the rule); open/close_span are unambiguous
    _TRACERISH = ("tr", "tracer")

    def files(self, module: ParsedModule) -> bool:
        return module.rel not in self.SKIP

    @staticmethod
    def _scopes(tree):
        yield tree
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    @staticmethod
    def _parents(scope) -> dict:
        """child -> parent map over the scope, not descending into
        nested defs (their spans are judged in their own scope)."""
        out = {}

        def build(node):
            for ch in ast.iter_child_nodes(node):
                out[ch] = node
                if not isinstance(ch, (ast.FunctionDef,
                                       ast.AsyncFunctionDef,
                                       ast.ClassDef, ast.Lambda)):
                    build(ch)

        build(scope)
        return out

    def _consumption(self, call, parents):
        """How the span-call's value is consumed: ``('with', None)``,
        ``('escape', None)``, ``('discard', None)``, or
        ``('name', ident)`` for a trackable simple-name binding."""
        ch, p = call, parents.get(call)
        while p is not None:
            if isinstance(p, ast.withitem):
                return ("with" if p.context_expr is ch else "escape", None)
            if isinstance(p, ast.Call):
                # argument position (close_span(sp), self._watch(sp, ...))
                return ("escape", None)
            if isinstance(p, (ast.Return, ast.Yield, ast.YieldFrom)):
                return ("escape", None)
            if isinstance(p, ast.Assign):
                if len(p.targets) == 1 \
                        and isinstance(p.targets[0], ast.Name):
                    return ("name", p.targets[0].id)
                return ("escape", None)   # attr/subscript/tuple target
            if isinstance(p, ast.Expr):
                return ("discard", None)
            if isinstance(p, (ast.IfExp, ast.BoolOp, ast.Await,
                              ast.NamedExpr)):
                ch, p = p, parents.get(p)   # x = a if c else open_span()
                continue
            # attribute read / comparison off the fresh value — give up
            # tracking rather than guess (under-report)
            return ("escape", None)
        return ("escape", None)

    def _name_is_settled(self, name, binder, scope, parents):
        """True when some use of ``name`` after its binding closes or
        escapes the span: call argument, method call on it, return /
        yield, re-assignment, or a ``with`` entry."""
        after = (binder.lineno, binder.col_offset)
        for node in _walk_in_order(scope):
            if not (isinstance(node, ast.Name) and node.id == name
                    and isinstance(node.ctx, ast.Load)
                    and (node.lineno, node.col_offset) > after):
                continue
            ch, p = node, parents.get(node)
            while p is not None:
                if isinstance(p, ast.Call):
                    # arg of any call — close_span(sp) and every other
                    # hand-off — or a method call sp.xxx(...) via func
                    return True
                if isinstance(p, (ast.Return, ast.Yield, ast.YieldFrom)):
                    return True
                if isinstance(p, ast.Assign) and p.value is ch:
                    return True             # aliased / stored
                if isinstance(p, ast.withitem) and p.context_expr is ch:
                    return True
                if isinstance(p, (ast.Attribute, ast.IfExp, ast.BoolOp,
                                  ast.NamedExpr)):
                    ch, p = p, parents.get(p)
                    continue
                break                       # plain read (compare, if sp:)
        return False

    def check(self, module: ParsedModule):
        findings = []
        for scope in self._scopes(module.tree):
            parents = self._parents(scope)
            for node in _walk_in_order(scope):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("span", "open_span")):
                    continue
                kind = node.func.attr
                if kind == "span":
                    recv = dotted_name(node.func.value).split(".")[-1]
                    if not (recv in self._TRACERISH or "tracer" in recv):
                        continue
                use, ident = self._consumption(node, parents)
                if use in ("with", "escape"):
                    continue
                if use == "name" and self._name_is_settled(
                        ident, node, scope, parents):
                    continue
                if kind == "span":
                    msg = (".span(...) returns a context manager — "
                           "without `with` the span never even opens "
                           "(use `with tracer.span(...)`)")
                else:
                    held = f" bound to '{ident}'" if ident else ""
                    msg = (f"open_span(...) result{held} is never "
                           "close_span'd and never escapes this scope — "
                           "an unclosed span emits no trace record and "
                           "orphans its children in the span tree")
                findings.append(Finding(module.rel, node.lineno,
                                        self.id, msg))
        return findings


#: registration order == report order for equal (file, line)
ALL_RULES = (HostSyncRule(), PrngKeyReuseRule(), DtypeDisciplineRule(),
             RaiseDisciplineRule(), DonatedBufferReuseRule(),
             NoBarePrintRule(), MetricsCatalogRule(), PallasCoverageRule(),
             SpanLeakRule())

RULES_BY_ID = {r.id: r for r in ALL_RULES}
