"""Pallas TPU kernel for the stacked Taylor-mode derivative table.

The XLA version (:func:`~.taylor.taylor_derivatives`) streams each layer's
channel-stacked activations through HBM — at N=50k points and width 128 with
4 channels that is ~100 MB per layer per sweep, and HBM bandwidth becomes the
step-time floor.  This kernel tiles the point batch and keeps the ENTIRE
wavefront — every layer, every derivative channel — resident in VMEM for the
tile, so HBM traffic collapses to: collocation points in, derivative tables
out, plus the (tiny, VMEM-resident) weights.

Two kernels share one body:

* **forward** — runs the same pure :func:`taylor_derivatives` math on a
  ``[tile, d]`` block with the weights read from VMEM refs.
* **backward** — recomputes the tile's propagation and reverse-differentiates
  it *inside* the kernel via ``jax.vjp`` (trace-time transform: the
  transposed matmuls and tanh-chain products lower to Mosaic like any other
  ops), accumulating weight/bias cotangents across the sequential grid and
  emitting the per-tile point cotangent (so gradient-based collocation
  adaptation differentiating through the table stays correct).

Wrapped in ``jax.custom_vjp`` and exposed as a drop-in table producer for
:func:`~.fused.make_fused_residual`.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pragma: no cover - import guard exercised only off-TPU
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from .taylor import taylor_derivatives


def _sorted_mis(requests: set) -> list:
    return sorted(set(requests) | {()}, key=lambda t: (len(t), t))


def available() -> bool:
    """True when the TPU pallas backend can run (real TPU present)."""
    return _HAS_PLTPU and jax.default_backend() == "tpu"


def build_pallas_table_fn(requests: set, layer_shapes: Sequence[tuple],
                          tile: int = 1024, precision=None,
                          interpret: bool = False, compute_dtype=None):
    """Build ``table_fn(layers, X) -> {mi: [N, n_out]}`` backed by the fused
    pallas kernels.

    Args:
      requests: canonical multi-indices (the primal ``()`` is implied).
      layer_shapes: ``[(in, out), ...]`` static layer dims for spec building.
      tile: points per grid step (VMEM working set scales with
        ``tile × width × channels × layers``).
      precision: matmul precision inside the kernel.
      interpret: run in interpreter mode (CPU testing).
      compute_dtype: mixed-precision matmul operands inside the kernel
        (e.g. ``jnp.bfloat16`` for the MXU's native single-pass path) with
        float32 accumulation; see :func:`~.taylor.taylor_derivatives`.
    """
    mis = _sorted_mis(requests)
    n_layers = len(layer_shapes)
    d_in = layer_shapes[0][0]
    n_out = layer_shapes[-1][1]

    def tile_table(layers, x):
        table = taylor_derivatives(list(layers), x, set(mis),
                                   precision=precision, flat_matmul=True,
                                   compute_dtype=compute_dtype)
        return tuple(table[mi] for mi in mis)

    # ---------------- forward kernel ----------------
    def fwd_kernel(*refs):
        x_ref = refs[0]
        w_refs = refs[1:1 + 2 * n_layers]
        out_refs = refs[1 + 2 * n_layers:]
        layers = [(w_refs[2 * i][...], w_refs[2 * i + 1][...])
                  for i in range(n_layers)]
        outs = tile_table(layers, x_ref[...])
        for ref, val in zip(out_refs, outs):
            ref[...] = val

    # ---------------- backward kernel ----------------
    def bwd_kernel(*refs):
        x_ref = refs[0]
        w_refs = refs[1:1 + 2 * n_layers]
        g_refs = refs[1 + 2 * n_layers:1 + 2 * n_layers + len(mis)]
        dw_refs = refs[1 + 2 * n_layers + len(mis):-1]
        dx_ref = refs[-1]
        layers = tuple((w_refs[2 * i][...], w_refs[2 * i + 1][...])
                       for i in range(n_layers))
        x = x_ref[...]

        def f(layers, x):
            return tile_table(layers, x)

        _, vjp = jax.vjp(f, layers, x)
        grads, dx = vjp(tuple(g[...] for g in g_refs))
        dx_ref[...] = dx

        i = pl.program_id(0)
        for li, (gW, gb) in enumerate(grads):
            dW_ref, db_ref = dw_refs[2 * li], dw_refs[2 * li + 1]

            @pl.when(i == 0)
            def _(dW_ref=dW_ref, db_ref=db_ref, gW=gW, gb=gb):
                dW_ref[...] = gW
                db_ref[...] = gb

            @pl.when(i != 0)
            def _(dW_ref=dW_ref, db_ref=db_ref, gW=gW, gb=gb):
                dW_ref[...] += gW
                db_ref[...] += gb

    # the backward kernel re-runs the propagation AND holds its VJP
    # residuals in VMEM — at the forward tile it blows the ~16 MB scoped
    # VMEM budget, so it gets a smaller point tile (more grid steps, same
    # math; the dW accumulation across steps already handles any grid size)
    tile_bwd = max(128, tile // 4)

    def _whole(shape):  # weight-style block: resident across the grid
        return pl.BlockSpec(shape, lambda i: tuple(0 for _ in shape))

    def _tiled(ncols, t=tile):  # point-axis block
        return pl.BlockSpec((t, ncols), lambda i: (i, 0))

    # biases travel as [1, fan_out]: Mosaic wants >=2-D refs; broadcasting
    # against [tile, fan_out] chunks is unchanged
    w_specs = []
    for (fan_in, fan_out) in layer_shapes:
        w_specs.append(_whole((fan_in, fan_out)))
        w_specs.append(_whole((1, fan_out)))

    def _pad(X, t=tile):
        N = X.shape[0]
        n_tiles = -(-N // t)
        pad = n_tiles * t - N
        if pad:
            X = jnp.concatenate([X, jnp.zeros((pad, X.shape[1]), X.dtype)], 0)
        return X, n_tiles, N

    def _forward(flat_layers, X):
        Xp, n_tiles, N = _pad(X)
        outs = pl.pallas_call(
            fwd_kernel,
            grid=(n_tiles,),
            in_specs=[_tiled(d_in)] + w_specs,
            out_specs=[_tiled(n_out) for _ in mis],
            out_shape=[jax.ShapeDtypeStruct((Xp.shape[0], n_out), X.dtype)
                       for _ in mis],
            interpret=interpret,
        )(Xp, *flat_layers)
        return tuple(o[:N] for o in outs)

    def _backward(flat_layers, X, gs):
        Xp, n_tiles, N = _pad(X, tile_bwd)
        pad = Xp.shape[0] - N
        if pad:  # zero cotangents on padded rows: no gradient contribution
            gs = tuple(jnp.concatenate(
                [g, jnp.zeros((pad, n_out), g.dtype)], 0) for g in gs)
        outs = pl.pallas_call(
            bwd_kernel,
            grid=(n_tiles,),
            in_specs=[_tiled(d_in, tile_bwd)] + w_specs
            + [_tiled(n_out, tile_bwd) for _ in mis],
            out_specs=w_specs + [_tiled(d_in, tile_bwd)],
            out_shape=[jax.ShapeDtypeStruct(s, X.dtype)
                       for (fi, fo) in layer_shapes
                       for s in ((fi, fo), (1, fo))]
            + [jax.ShapeDtypeStruct(Xp.shape, X.dtype)],
            interpret=interpret,
        )(Xp, *flat_layers, *gs)
        return tuple(outs[:-1]), outs[-1][:N]

    @jax.custom_vjp
    def table(flat_layers, X):
        return _forward(flat_layers, X)

    def table_fwd(flat_layers, X):
        return _forward(flat_layers, X), (flat_layers, X)

    def table_bwd(res, gs):
        flat_layers, X = res
        dws, dX = _backward(flat_layers, X, tuple(gs))
        return dws, dX

    table.defvjp(table_fwd, table_bwd)

    def table_fn(layers, X):
        # bias reshape to [1, fan_out] happens in traced code, so its
        # transpose is handled by the outer AD, not the custom vjp
        flat = tuple(arr if arr.ndim == 2 else arr.reshape(1, -1)
                     for pair in layers for arr in pair)
        outs = table(flat, X)
        return dict(zip(mis, outs))

    return table_fn
