"""Residual-based adaptive collocation resampling (beyond-reference).

The reference trains on one fixed Latin-Hypercube draw for the whole run
(``domains.py:12-20``); every retrieved adaptive-collocation result
(PACMANN, arXiv:2411.19632; importance sampling for PINNs, arXiv:2104.12325)
says the same budget converges faster when points concentrate where the PDE
residual is large.  This module adds that as a *redraw*, not a point-mover:

* every ``resample_every`` epochs (at a chunk boundary of the jitted Adam
  scan), draw a fresh LHS **pool** of ``pool_factor x N_f`` candidates,
* score the pool with the solver's compiled residual (one jitted forward,
  data-parallel across the mesh under ``dist=True``; on a multi-HOST mesh
  every process draws the identical pool, scores its addressable shards,
  and a ``process_allgather`` of the per-row scores makes the importance
  selection bit-identical on all hosts — no cross-host array fetch),
* keep ``N_f`` points by importance sampling ``p ∝ |f|^temp`` mixed with a
  ``uniform_frac`` floor (coverage never collapses onto one feature),
  drawn without replacement via the Gumbel top-k trick (O(pool), no
  sequential host loop).

TPU-shaped by construction: ``N_f`` is constant, so the training step's
compiled program, optimizer state, and (under ``dist``) the ``"data"``
sharding layout are all reused — the host only swaps the buffer contents
between device chunks.  Incompatible with *per-point* residual λ
(Adaptive_type=1): those weights are row-aligned with their points and have
trained ascent state; the solver raises rather than silently re-seeding
them (scalar/outside-sum and NTK weighting compose fine).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..utils import LatinHypercubeSample


def importance_select(scores: np.ndarray, n_keep: int, temp: float = 1.0,
                      uniform_frac: float = 0.1,
                      rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """Indices of ``n_keep`` rows drawn without replacement with probability
    ``∝ (1-u)·|s|^temp/Σ + u/N`` — Gumbel top-k, vectorized.

    ``uniform_frac=1`` degenerates to a uniform redraw; ``temp`` sharpens
    (>1) or flattens (<1) the residual concentration."""
    rng = rng or np.random.default_rng(0)
    s = np.abs(np.asarray(scores, np.float64)).ravel()
    if n_keep >= s.size:
        return np.arange(s.size)
    # normalize before exponentiating: s**temp can overflow to inf for
    # extreme residuals with temp>1, which would silently disable the
    # importance weighting exactly when residuals are most informative
    # (advisor finding, round 2); p is scale-invariant after the /tot below
    smax = s.max()
    if smax > 0.0 and np.isfinite(smax):
        s = s / smax
    p = s ** temp
    tot = p.sum()
    if not np.isfinite(tot) or tot <= 0.0:
        p = np.full(s.size, 1.0 / s.size)
    else:
        p = (1.0 - uniform_frac) * p / tot + uniform_frac / s.size
    gumbel = rng.gumbel(size=s.size)
    keys = np.log(p) + gumbel
    return np.argpartition(-keys, n_keep)[:n_keep]


def _row_scores(values) -> np.ndarray:
    """Per-row score of one residual block: |f| in float64, summed over
    output columns.  The ONE reduction both the single-host and multi-host
    scoring paths share — they must stay bitwise-identical for a resampled
    run to reproduce across topologies (test_multihost asserts this)."""
    a = np.abs(np.asarray(values, np.float64))
    return a.reshape(a.shape[0], -1).sum(axis=1)


def residual_scores(residual_fn: Callable, params, X) -> np.ndarray:
    """``[N]`` importance scores: |residual| summed over outputs/equations."""
    f = residual_fn(params, X)
    parts = f if isinstance(f, tuple) else (f,)
    s = None
    for part in parts:
        a = _row_scores(part)
        s = a if s is None else s + a
    return s


def _scores_multihost(residual_fn: Callable, params, X_global,
                      n_pool: int) -> np.ndarray:
    """``[n_pool]`` global scores when the pool spans multiple processes.

    ``np.asarray`` on a cross-host array is illegal, so each process reads
    only its addressable shards (row slices of the global pool), and the
    (row, score) pairs ride ONE ``process_allgather`` — every process then
    holds the full score vector and the subsequent seeded selection is
    bit-identical everywhere."""
    from jax.experimental import multihost_utils

    f = residual_fn(params, X_global)
    parts = f if isinstance(f, tuple) else (f,)
    local: dict[int, np.ndarray] = {}
    for part in parts:
        for shard in part.addressable_shards:
            a = _row_scores(shard.data)
            start = shard.index[0].start or 0
            local[start] = local.get(start, 0.0) + a
    rows = np.concatenate([np.arange(s, s + v.size)
                           for s, v in sorted(local.items())])
    vals = np.concatenate([v for _, v in sorted(local.items())])
    # one collective: rows ride along as a float64 lane (exact up to 2^53)
    packed = np.stack([rows.astype(np.float64), vals])
    packed_all = np.asarray(multihost_utils.process_allgather(packed))
    packed_all = packed_all.reshape(-1, 2, packed.shape[1])
    scores = np.zeros(n_pool, np.float64)
    for block in packed_all:
        scores[block[0].astype(np.int64)] = block[1]
    return scores


def make_residual_resampler(residual_fn: Callable, xlimits: np.ndarray,
                            n_f: int, *, pool_factor: int = 4,
                            temp: float = 1.0, uniform_frac: float = 0.1,
                            seed: int = 0,
                            like=None) -> Callable:
    """Build ``resample(params, epoch) -> X_new`` for the fit loop.

    ``like``: an existing (possibly sharded) collocation array — the fresh
    pool and the selected points are placed with its sharding so the redraw
    is transparent to a ``dist=True`` compiled step.  Each call uses a
    different pool seed (``seed + epoch``) so successive redraws explore."""
    placement = getattr(like, "sharding", None)
    n_pool = max(int(pool_factor) * n_f, n_f)
    if placement is not None and getattr(placement, "mesh", None) is not None:
        n_dev = int(np.prod(placement.mesh.devices.shape))
        # fail at build time, not mid-training: the selected X_new has n_f
        # rows and must device_put onto the mesh, so n_f itself (not just
        # the pool) has to shard evenly (advisor finding, round 2 — the
        # earlier fix only rounded the pool and moved the shape error two
        # lines down).  n_pool = pool_factor*n_f is then divisible too.
        if n_f % n_dev:
            raise ValueError(
                f"n_f={n_f} must be divisible by the mesh device count "
                f"{n_dev} for resampling under dist=True")
    assert n_pool >= n_f, (n_pool, n_f)

    multihost = jax.process_count() > 1
    if multihost and placement is None:
        raise ValueError(
            "multi-host resampling needs a sharded `like` array so the "
            "fresh pool can be placed on the global mesh")

    def _place(arr_np):
        """float32 device array with the training placement.  Multi-host:
        every process holds the identical numpy array, so assembling the
        global array from per-shard row slices is consistent."""
        arr_np = np.asarray(arr_np, np.float32)
        if multihost:
            return jax.make_array_from_callback(
                arr_np.shape, placement, lambda idx: arr_np[idx])
        out = jnp.asarray(arr_np)
        return jax.device_put(out, placement) if placement is not None else out

    def resample(params, epoch: int) -> jnp.ndarray:
        # two decorrelated streams per redraw (pool LHS vs selection noise),
        # both keyed on (seed, epoch) so distinct epochs explore new pools —
        # and therefore identical on every process of a multi-host mesh
        pool_ss, sel_ss = np.random.SeedSequence([seed, int(epoch)]).spawn(2)
        pool = LatinHypercubeSample(n_pool, xlimits, criterion="c",
                                    seed=int(pool_ss.generate_state(1)[0]))
        pool_j = _place(pool)
        if multihost:
            scores = _scores_multihost(residual_fn, params, pool_j, n_pool)
        else:
            scores = residual_scores(residual_fn, params, pool_j)
        rng = np.random.default_rng(sel_ss)
        idx = importance_select(scores, n_f, temp=temp,
                                uniform_frac=uniform_frac, rng=rng)
        X_np = np.asarray(pool[np.sort(idx)], np.float32)
        # host copy for callers that must read the live set without touching
        # the device array (NTK subsample on multi-process meshes) —
        # identical on every process by seed determinism
        resample.last_host = X_np
        return _place(X_np)

    return resample
