"""Residual-based adaptive collocation resampling (beyond-reference).

The reference trains on one fixed Latin-Hypercube draw for the whole run
(``domains.py:12-20``); every retrieved adaptive-collocation result
(PACMANN, arXiv:2411.19632; importance sampling for PINNs, arXiv:2104.12325)
says the same budget converges faster when points concentrate where the PDE
residual is large.  This module adds that as a *redraw*, not a point-mover:

* every ``resample_every`` epochs (at a chunk boundary of the jitted Adam
  scan), draw a fresh LHS **pool** of ``pool_factor x N_f`` candidates,
* score the pool with the solver's compiled residual (one jitted forward,
  data-parallel across a single host's mesh under ``dist=True``; scoring
  gathers |f| to the host, so a multi-*host* mesh raises up front),
* keep ``N_f`` points by importance sampling ``p ∝ |f|^temp`` mixed with a
  ``uniform_frac`` floor (coverage never collapses onto one feature),
  drawn without replacement via the Gumbel top-k trick (O(pool), no
  sequential host loop).

TPU-shaped by construction: ``N_f`` is constant, so the training step's
compiled program, optimizer state, and (under ``dist``) the ``"data"``
sharding layout are all reused — the host only swaps the buffer contents
between device chunks.  Incompatible with *per-point* residual λ
(Adaptive_type=1): those weights are row-aligned with their points and have
trained ascent state; the solver raises rather than silently re-seeding
them (scalar/outside-sum and NTK weighting compose fine).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..utils import LatinHypercubeSample


def importance_select(scores: np.ndarray, n_keep: int, temp: float = 1.0,
                      uniform_frac: float = 0.1,
                      rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """Indices of ``n_keep`` rows drawn without replacement with probability
    ``∝ (1-u)·|s|^temp/Σ + u/N`` — Gumbel top-k, vectorized.

    ``uniform_frac=1`` degenerates to a uniform redraw; ``temp`` sharpens
    (>1) or flattens (<1) the residual concentration."""
    rng = rng or np.random.default_rng(0)
    s = np.abs(np.asarray(scores, np.float64)).ravel()
    if n_keep >= s.size:
        return np.arange(s.size)
    # normalize before exponentiating: s**temp can overflow to inf for
    # extreme residuals with temp>1, which would silently disable the
    # importance weighting exactly when residuals are most informative
    # (advisor finding, round 2); p is scale-invariant after the /tot below
    smax = s.max()
    if smax > 0.0 and np.isfinite(smax):
        s = s / smax
    p = s ** temp
    tot = p.sum()
    if not np.isfinite(tot) or tot <= 0.0:
        p = np.full(s.size, 1.0 / s.size)
    else:
        p = (1.0 - uniform_frac) * p / tot + uniform_frac / s.size
    gumbel = rng.gumbel(size=s.size)
    keys = np.log(p) + gumbel
    return np.argpartition(-keys, n_keep)[:n_keep]


def residual_scores(residual_fn: Callable, params, X) -> np.ndarray:
    """``[N]`` importance scores: |residual| summed over outputs/equations."""
    f = residual_fn(params, X)
    parts = f if isinstance(f, tuple) else (f,)
    s = None
    for part in parts:
        a = np.abs(np.asarray(part, np.float64))
        a = a.reshape(a.shape[0], -1).sum(axis=1)
        s = a if s is None else s + a
    return s


def make_residual_resampler(residual_fn: Callable, xlimits: np.ndarray,
                            n_f: int, *, pool_factor: int = 4,
                            temp: float = 1.0, uniform_frac: float = 0.1,
                            seed: int = 0,
                            like=None) -> Callable:
    """Build ``resample(params, epoch) -> X_new`` for the fit loop.

    ``like``: an existing (possibly sharded) collocation array — the fresh
    pool and the selected points are placed with its sharding so the redraw
    is transparent to a ``dist=True`` compiled step.  Each call uses a
    different pool seed (``seed + epoch``) so successive redraws explore."""
    placement = getattr(like, "sharding", None)
    n_pool = max(int(pool_factor) * n_f, n_f)
    if placement is not None and getattr(placement, "mesh", None) is not None:
        n_dev = int(np.prod(placement.mesh.devices.shape))
        # fail at build time, not mid-training: the selected X_new has n_f
        # rows and must device_put onto the mesh, so n_f itself (not just
        # the pool) has to shard evenly (advisor finding, round 2 — the
        # earlier fix only rounded the pool and moved the shape error two
        # lines down).  n_pool = pool_factor*n_f is then divisible too.
        if n_f % n_dev:
            raise ValueError(
                f"n_f={n_f} must be divisible by the mesh device count "
                f"{n_dev} for resampling under dist=True")
    assert n_pool >= n_f, (n_pool, n_f)

    if jax.process_count() > 1:
        raise NotImplementedError(
            "adaptive resampling on a multi-host mesh is not supported yet: "
            "pool scoring gathers |f| to the host, which cannot fetch a "
            "cross-host array")

    def resample(params, epoch: int) -> jnp.ndarray:
        # two decorrelated streams per redraw (pool LHS vs selection noise),
        # both keyed on (seed, epoch) so distinct epochs explore new pools
        pool_ss, sel_ss = np.random.SeedSequence([seed, int(epoch)]).spawn(2)
        pool = LatinHypercubeSample(n_pool, xlimits, criterion="c",
                                    seed=int(pool_ss.generate_state(1)[0]))
        pool_j = jnp.asarray(pool, jnp.float32)
        if placement is not None:
            pool_j = jax.device_put(pool_j, placement)
        scores = residual_scores(residual_fn, params, pool_j)
        rng = np.random.default_rng(sel_ss)
        idx = importance_select(scores, n_f, temp=temp,
                                uniform_frac=uniform_frac, rng=rng)
        X_new = jnp.asarray(pool[np.sort(idx)], jnp.float32)
        if placement is not None:
            X_new = jax.device_put(X_new, placement)
        return X_new

    return resample
