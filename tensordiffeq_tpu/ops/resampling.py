"""Residual-based adaptive collocation resampling (beyond-reference).

The reference trains on one fixed Latin-Hypercube draw for the whole run
(``domains.py:12-20``); every retrieved adaptive-collocation result
(PACMANN, arXiv:2411.19632; importance sampling for PINNs, arXiv:2104.12325)
says the same budget converges faster when points concentrate where the PDE
residual is large.  This module adds that as a *redraw*, not a point-mover:

* every ``resample_every`` epochs (at a chunk boundary of the jitted Adam
  scan), draw a fresh LHS **pool** of ``pool_factor x N_f`` candidates,
* score the pool with the solver's compiled residual (one jitted forward,
  data-parallel across the mesh under ``dist=True``; on a multi-HOST mesh
  every process draws the identical pool, scores its addressable shards,
  and a ``process_allgather`` of the per-row scores makes the importance
  selection bit-identical on all hosts — no cross-host array fetch),
* keep ``N_f`` points by importance sampling ``p ∝ |f|^temp`` mixed with a
  ``uniform_frac`` floor (coverage never collapses onto one feature),
  drawn without replacement via the Gumbel top-k trick (O(pool), no
  sequential host loop).

TPU-shaped by construction: ``N_f`` is constant, so the training step's
compiled program, optimizer state, and (under ``dist``) the ``"data"``
sharding layout are all reused — the host only swaps the buffer contents
between device chunks.

Two implementations share the selection semantics:

* the original **host path** (:func:`make_residual_resampler`): numpy LHS
  pool, scores pulled to the host, numpy Gumbel top-k, ``device_put``
  back.  Kept as the ``resample_device=False`` fallback and the
  cross-implementation reference.  Incompatible with *per-point* residual
  λ (Adaptive_type=1) — its pool is entirely fresh, so there are no rows
  to carry trained λ for;
* the **device path** (:class:`DeviceResampler`): pool generation
  (``jax.random``, stratified per dimension so LHS-like coverage
  survives), residual scoring under the existing ``"data"`` sharding, and
  Gumbel top-k via ``jax.lax.top_k`` in ONE jitted program — no host copy
  of pool or scores, and on multi-host meshes the selection consumes the
  globally-sharded scores directly (no ``process_allgather``).  Its pool
  is ``[current X_f ; fresh candidates]`` (PACMANN-style), so selected
  rows with index < N_f are *kept* points whose per-point λ (and λ-ascent
  moments) ride through the redraw — lifting the Adaptive_type=1
  restriction.

A third arm implements PACMANN's *ascent* mover proper
(:class:`AscentResampler`, ``resample_mode="ascent"``): rather than
drawing a pool and selecting, it moves the retained points K
normalized-gradient steps UP the residual-magnitude landscape
(domain-clipped), keeping a stratified ``fresh_frac`` coverage draw in
place of the lowest-score rows.  When the solver's fused minimax unit is
adopted, the per-point scores and the ascent direction both fall out of
ONE ``jax.vjp`` of the fused ``sq`` — the ``∂/∂w`` cotangent is exactly
``f²`` per point/equation and ``∂/∂X`` is the move direction — so the
score pass costs no differentiation beyond what the training step
already fuses.  Moved rows keep their row index (``idx = row``), so
per-point λ and its ascent moments ride through the move untouched.
:class:`FamilyAscentResampler` is the same mover vmapped over the
surrogate-factory model axis.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..utils import LatinHypercubeSample


def importance_select(scores: np.ndarray, n_keep: int, temp: float = 1.0,
                      uniform_frac: float = 0.1,
                      rng: Optional[np.random.Generator] = None) -> np.ndarray:
    """Indices of ``n_keep`` rows drawn without replacement with probability
    ``∝ (1-u)·|s|^temp/Σ + u/N`` — Gumbel top-k, vectorized.

    ``uniform_frac=1`` degenerates to a uniform redraw; ``temp`` sharpens
    (>1) or flattens (<1) the residual concentration."""
    rng = rng or np.random.default_rng(0)
    # tdq: allow[dtype-discipline] host-side selection math (reference path): f64 keeps Gumbel keys exact, never enters a device program
    s = np.abs(np.asarray(scores, np.float64)).ravel()
    if n_keep >= s.size:
        return np.arange(s.size)
    # normalize before exponentiating: s**temp can overflow to inf for
    # extreme residuals with temp>1, which would silently disable the
    # importance weighting exactly when residuals are most informative
    # (advisor finding, round 2); p is scale-invariant after the /tot below
    smax = s.max()
    if smax > 0.0 and np.isfinite(smax):
        s = s / smax
    p = s ** temp
    tot = p.sum()
    if not np.isfinite(tot) or tot <= 0.0:
        p = np.full(s.size, 1.0 / s.size)
    else:
        p = (1.0 - uniform_frac) * p / tot + uniform_frac / s.size
    gumbel = rng.gumbel(size=s.size)
    # clamp the floor before the log: with uniform_frac=0 a zero-residual
    # row has p=0 and log(0) = -inf poisons its key — the row becomes
    # permanently unselectable (plus a numpy RuntimeWarning) even when
    # n_keep exceeds the nonzero count.  The tiny floor keeps every row
    # reachable through its Gumbel noise while leaving nonzero
    # probabilities untouched at float64 scale.
    # tdq: allow[dtype-discipline] host-side f64 tiny-floor keeps zero-residual rows reachable without log(0)
    keys = np.log(np.maximum(p, np.finfo(np.float64).tiny)) + gumbel
    return np.argpartition(-keys, n_keep)[:n_keep]


def _row_scores(values) -> np.ndarray:
    """Per-row score of one residual block: |f| in float64, summed over
    output columns.  The ONE reduction both the single-host and multi-host
    scoring paths share — they must stay bitwise-identical for a resampled
    run to reproduce across topologies (test_multihost asserts this)."""
    # tdq: allow[dtype-discipline] host-side score accumulation in f64 so summed |f| never saturates
    a = np.abs(np.asarray(values, np.float64))
    return a.reshape(a.shape[0], -1).sum(axis=1)


def residual_scores(residual_fn: Callable, params, X) -> np.ndarray:
    """``[N]`` importance scores: |residual| summed over outputs/equations."""
    f = residual_fn(params, X)
    parts = f if isinstance(f, tuple) else (f,)
    s = None
    for part in parts:
        a = _row_scores(part)
        s = a if s is None else s + a
    return s


def _allgather_by_row(local: dict, n: int) -> np.ndarray:
    """Assemble the full ``[n, w]`` float64 array every process agrees on
    from per-process row slices (``local``: global start row → this
    process's values for that slice, ``[k]`` or ``[k, w]``).

    ``np.asarray`` on a cross-host array is illegal, so the (row, values)
    pairs ride ONE ``process_allgather`` — row indices travel as a
    float64 lane (exact up to 2^53) and each block scatters back into
    place, so the result is bit-identical everywhere.  The one packing
    scheme both the score path and the X_f-gather path use."""
    from jax.experimental import multihost_utils

    rows = np.concatenate([np.arange(s, s + v.shape[0])
                           for s, v in sorted(local.items())])
    # tdq: allow[dtype-discipline] the multihost row-lane packing CONTRACT: one f64 allgather lane, exact to 2^53
    vals = np.concatenate([np.asarray(v, np.float64).reshape(v.shape[0], -1)
                           for _, v in sorted(local.items())])
    # tdq: allow[dtype-discipline] row indices ride the same f64 lane (exact integers up to 2^53)
    packed = np.concatenate([rows[:, None].astype(np.float64), vals], axis=1)
    packed_all = np.asarray(multihost_utils.process_allgather(packed))
    packed_all = packed_all.reshape(-1, packed.shape[1])
    # tdq: allow[dtype-discipline] host-side scatter target of the f64 allgather lane
    out = np.zeros((n, vals.shape[1]), np.float64)
    out[packed_all[:, 0].astype(np.int64)] = packed_all[:, 1:]
    return out


def _scores_multihost(residual_fn: Callable, params, X_global,
                      n_pool: int) -> np.ndarray:
    """``[n_pool]`` global scores when the pool spans multiple processes:
    each process scores only its addressable shards (row slices of the
    global pool) and :func:`_allgather_by_row` assembles the full vector,
    so the subsequent seeded selection is bit-identical everywhere."""
    f = residual_fn(params, X_global)
    parts = f if isinstance(f, tuple) else (f,)
    local: dict[int, np.ndarray] = {}
    for part in parts:
        for shard in part.addressable_shards:
            a = _row_scores(shard.data)
            start = shard.index[0].start or 0
            local[start] = local.get(start, 0.0) + a
    return _allgather_by_row(local, n_pool)[:, 0]


class ResampleSwap(NamedTuple):
    """One device redraw's results, still device-resident.

    ``X_new``: the selected ``[n_f, d]`` collocation set (training
    placement applied).  ``idx``: each new row's pool index, sorted
    ascending; a value ``< n_f`` means the row is a *kept* current point
    (``idx`` then IS its old row index — the λ-carry gather map).
    ``kept``: boolean mask ``idx < n_f``.  ``stats``: scalar diagnostics
    (``kept_fraction``, ``score_gain`` = mean selected |f| over mean pool
    |f|) — read them on the host only at swap time, so the dispatch stays
    asynchronous."""

    X_new: jnp.ndarray
    idx: jnp.ndarray
    kept: jnp.ndarray
    stats: dict


def _stratified_pool(key, n: int, xlimits) -> jnp.ndarray:
    """``[n, d]`` LHS-like stratified draw with ``jax.random``: each
    dimension splits its range into ``n`` equal strata, places one sample
    per stratum, and shuffles strata independently per dimension — the
    same marginal coverage guarantee as a Latin Hypercube (random
    pairing), with no host RNG in the loop."""
    d = xlimits.shape[0]
    ks = jax.random.split(key, 2 * d)
    cols = []
    for j in range(d):
        lo, hi = float(xlimits[j, 0]), float(xlimits[j, 1])
        strata = jax.random.permutation(ks[2 * j], n).astype(jnp.float32)
        u = jax.random.uniform(ks[2 * j + 1], (n,), jnp.float32)
        cols.append(lo + (strata + u) / n * (hi - lo))
    return jnp.stack(cols, axis=1)


def _gumbel_topk_device(scores, n_keep: int, temp: float,
                        uniform_frac: float, key):
    """Device-side Gumbel top-k over ``p ∝ (1-u)·|s|^temp/Σ + u/N`` —
    the same distribution :func:`importance_select` draws on the host,
    with the same degenerate-score fallbacks (overflow/zero-sum →
    uniform; zero rows floored so they stay reachable)."""
    s = jnp.abs(scores)
    n = s.shape[0]
    smax = jnp.max(s)
    s = jnp.where((smax > 0.0) & jnp.isfinite(smax), s / smax, s)
    p = s ** temp
    tot = jnp.sum(p)
    p = jnp.where(jnp.isfinite(tot) & (tot > 0.0),
                  (1.0 - uniform_frac) * p / tot + uniform_frac / n,
                  1.0 / n)
    p = jnp.maximum(p, jnp.finfo(jnp.float32).tiny)
    keys = jnp.log(p) + jax.random.gumbel(key, (n,), jnp.float32)
    _, idx = jax.lax.top_k(keys, n_keep)
    return jnp.sort(idx)


class DeviceResampler:
    """Device-resident adaptive redraw: pool → score → select in ONE
    jitted program, no host copy of pool or scores.

    The pool is ``[current X_f ; n_fresh stratified candidates]``
    (``n_fresh = max(pool_factor - 1, 1) × n_f``), so kept rows carry
    their trained per-point λ through the redraw (:func:`carry_rows`).
    Under a ``dist`` mesh every array keeps the training ``"data"``
    sharding end to end; on multi-host meshes the jitted program consumes
    the globally-sharded scores directly — no ``process_allgather``, no
    per-process numpy assembly.

    Calling :meth:`redraw` only *dispatches* the program (jax async
    dispatch): the host regains control in ~ms while the device works,
    which is what the fit loop's double-buffering hides behind the next
    training chunk.  Determinism: everything is keyed on
    ``fold_in(PRNGKey(seed), epoch)``, so a redraw is bit-reproducible
    across reruns and processes."""

    pipelined = True

    def __init__(self, residual_fn: Callable, xlimits: np.ndarray, n_f: int,
                 *, pool_factor: int = 4, temp: float = 1.0,
                 uniform_frac: float = 0.1, seed: int = 0, like=None):
        self.residual_fn = residual_fn
        # tdq: allow[dtype-discipline] domain limits held in f64 on the HOST; the jitted pool draw casts per-dim bounds to f32 scalars
        self.xlimits = np.asarray(xlimits, np.float64)
        self.n_f = int(n_f)
        self.temp = float(temp)
        self.uniform_frac = float(uniform_frac)
        self.seed = int(seed)
        self.n_fresh = max(int(pool_factor) - 1, 1) * self.n_f
        placement = getattr(like, "sharding", None)
        if placement is not None and getattr(placement, "mesh", None) is not None:
            n_dev = int(np.prod(placement.mesh.devices.shape))
            if self.n_f % n_dev:
                raise ValueError(
                    f"n_f={n_f} must be divisible by the mesh device count "
                    f"{n_dev} for resampling under dist=True")
            self.placement = placement
        else:
            self.placement = None
        self._redraw_jit = jax.jit(self._redraw_impl)

    # -- the one jitted program ---------------------------------------- #
    def _place(self, arr):
        if self.placement is None:
            return arr
        return jax.lax.with_sharding_constraint(arr, self.placement)

    def _redraw_impl(self, params, X_cur, epoch):
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        k_pool, k_sel = jax.random.split(key)
        fresh = self._place(_stratified_pool(k_pool, self.n_fresh,
                                             self.xlimits))
        pool = self._place(jnp.concatenate([X_cur, fresh], axis=0))
        f = self.residual_fn(params, pool)
        return _score_and_select(pool, f, self.n_f, self.temp,
                                 self.uniform_frac, k_sel, self.placement)

    def redraw(self, params, X_cur, epoch: int) -> ResampleSwap:
        """Dispatch one redraw (async — returns device futures)."""
        return self._redraw_jit(params, X_cur, jnp.asarray(int(epoch)))

    def lower_redraw(self, params, X_cur):
        """The redraw program's ``Lowered`` (cost analysis without a
        compile) — the score-pass FLOP pricing hook."""
        return self._redraw_jit.lower(params, X_cur, jnp.asarray(0))


def _score_and_select(pool, f, n_f: int, temp: float, uniform_frac: float,
                      k_sel, placement) -> "ResampleSwap":
    """The one score→select→stats block every device redraw shares
    (:class:`DeviceResampler` and :class:`FamilyResampler` per member):
    |residual| summed over components/columns, Gumbel top-k under the
    importance distribution, kept mask (pool index < ``n_f`` means a
    kept current point) and the kept_fraction / score_gain diagnostics.
    One implementation so a future scoring fix (the PR-10 ``log(0)``
    clamp class) cannot drift between the redraw flavors."""
    parts = f if isinstance(f, tuple) else (f,)
    scores = None
    for part in parts:
        a = jnp.abs(jnp.asarray(part, jnp.float32))
        a = jnp.sum(a.reshape(a.shape[0], -1), axis=1)
        scores = a if scores is None else scores + a
    idx = _gumbel_topk_device(scores, n_f, temp, uniform_frac, k_sel)
    X_new = jnp.take(pool, idx, axis=0)
    if placement is not None:
        X_new = jax.lax.with_sharding_constraint(X_new, placement)
    kept = idx < n_f
    sel_mean = jnp.mean(jnp.take(scores, idx))
    pool_mean = jnp.mean(scores)
    stats = {
        "kept_fraction": jnp.mean(kept.astype(jnp.float32)),
        "score_gain": sel_mean / jnp.maximum(
            pool_mean, jnp.finfo(jnp.float32).tiny),
    }
    return ResampleSwap(X_new, idx, kept, stats)


def _ascent_move(score_grad, X, xlimits, n_steps: int, step_frac: float):
    """Move every row ``n_steps`` normalized-gradient-ascent steps up the
    residual-magnitude landscape, clipped to the domain box after each
    step (PACMANN, arXiv:2411.19632).  ``score_grad(X) -> (s [N], g [N,
    d])`` supplies per-point scores ``s_p = Σ_e f_{e,p}²`` and their point
    gradient; the per-dimension step is ``step_frac`` of that dimension's
    extent, so anisotropic domains move proportionally.  The step must
    resolve the residual ridge it climbs: on Burgers the viscous shock is
    a few 1e-3 of the x-extent wide, and a 0.02 step overshoots it every
    iteration — points pile up PAST the ridge and the arm loses to the
    pool redraw (measured in ``bench.py --mode resample``; 0.005 recovers
    the win, hence the small default).  Returns ``(X_new, s_first,
    s_last)`` — the first/last evaluations bracket the move for the
    ``score_gain`` diagnostic."""
    lo = jnp.asarray(xlimits[:, 0], jnp.float32)
    hi = jnp.asarray(xlimits[:, 1], jnp.float32)
    step = step_frac * (hi - lo)
    s_first = None
    for _ in range(max(int(n_steps), 0)):
        s, g = score_grad(X)
        if s_first is None:
            s_first = s
        gn = jnp.sqrt(jnp.sum(g * g, axis=1, keepdims=True))
        X = jnp.clip(X + step * g / jnp.maximum(gn,
                                                jnp.finfo(jnp.float32).tiny),
                     lo, hi)
    s_last, _ = score_grad(X)
    if s_first is None:  # n_steps=0 degenerates to a no-op scoring pass
        s_first = s_last
    return X, s_first, s_last


class AscentResampler:
    """PACMANN-style gradient-ascent redraw (arXiv:2411.19632): instead of
    the pool→top-k draw, *move* the retained collocation points up the
    residual-magnitude gradient for K steps (domain-clipped), and replace
    only the ``fresh_frac`` lowest-score rows with a stratified fresh draw
    so coverage never collapses onto the ascended features.

    Same contract as :class:`DeviceResampler` — ``pipelined=True``, one
    jitted host-hop-free ``redraw(params, X_cur, epoch) -> ResampleSwap``
    the fit loop double-buffers behind a training chunk — but the swap's
    ``idx`` map is near-identity: a moved row keeps its row position
    (``idx = row``, ``kept=True``), so :func:`carry_rows` carries its
    per-point λ and λ-ascent moments through the move untouched (the
    point moves, its trained weight rides along); fresh rows index past
    ``n_f`` and re-initialize per the adaptive schedule.

    ``score_grad_fn(params, X) -> (scores [N], gX [N, d])`` lets the
    solver plug in the fused minimax unit: one ``jax.vjp`` of
    ``sq(layers, 1, X)`` yields the scores (the fused ``∂/∂w`` cotangent
    IS ``f²`` per point/equation) AND ``∂/∂X`` — the ascent direction
    costs no differentiation beyond what the fused step already computes.
    Without it, the default scores through ``residual_fn`` with one
    ``jax.value_and_grad``."""

    pipelined = True

    def __init__(self, residual_fn: Callable, xlimits: np.ndarray, n_f: int,
                 *, n_steps: int = 5, step_frac: float = 0.005,
                 fresh_frac: float = 0.1, seed: int = 0, like=None,
                 score_grad_fn: Optional[Callable] = None):
        self.residual_fn = residual_fn
        # tdq: allow[dtype-discipline] domain limits held in f64 on the HOST; the jitted move casts per-dim bounds to f32
        self.xlimits = np.asarray(xlimits, np.float64)
        self.n_f = int(n_f)
        self.n_steps = int(n_steps)
        self.step_frac = float(step_frac)
        self.seed = int(seed)
        self.n_fresh = int(round(max(min(float(fresh_frac), 1.0), 0.0)
                                 * self.n_f))
        self.score_grad_fn = score_grad_fn
        placement = getattr(like, "sharding", None)
        if placement is not None \
                and getattr(placement, "mesh", None) is not None:
            n_dev = int(np.prod(placement.mesh.devices.shape))
            if self.n_f % n_dev:
                raise ValueError(
                    f"n_f={n_f} must be divisible by the mesh device count "
                    f"{n_dev} for resampling under dist=True")
            self.placement = placement
        else:
            self.placement = None
        self._redraw_jit = jax.jit(self._redraw_impl)

    def _score_grad(self, params, X):
        if self.score_grad_fn is not None:
            return self.score_grad_fn(params, X)

        def total(Xv):
            f = self.residual_fn(params, Xv)
            parts = f if isinstance(f, tuple) else (f,)
            s = None
            for p in parts:
                a = jnp.sum(jnp.square(jnp.reshape(p, (Xv.shape[0], -1))),
                            axis=1)
                s = a if s is None else s + a
            return jnp.sum(s), s

        (_, s), g = jax.value_and_grad(total, has_aux=True)(X)
        return s, g

    def _place(self, arr):
        if self.placement is None:
            return arr
        return jax.lax.with_sharding_constraint(arr, self.placement)

    def _redraw_impl(self, params, X_cur, epoch):
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        X, s_first, s_last = _ascent_move(
            lambda Xv: self._score_grad(params, Xv), X_cur, self.xlimits,
            self.n_steps, self.step_frac)
        n_f = self.n_f
        row = jnp.arange(n_f)
        if self.n_fresh:
            fresh = _stratified_pool(key, self.n_fresh, self.xlimits)
            # the lowest-score rows contribute least where they stand:
            # recycle them as the stratified coverage draw
            _, worst = jax.lax.top_k(-s_last, self.n_fresh)
            is_fresh = jnp.zeros((n_f,), bool).at[worst].set(True)
            X = X.at[worst].set(fresh)
            fresh_rank = jnp.cumsum(is_fresh.astype(jnp.int32)) - 1
            idx = jnp.where(is_fresh, n_f + fresh_rank, row)
            kept = ~is_fresh
        else:
            idx, kept = row, jnp.ones((n_f,), bool)
        X = self._place(X)
        stats = {
            "kept_fraction": jnp.mean(kept.astype(jnp.float32)),
            # mean score after the move over mean score before it — the
            # ascent analogue of the pool path's selected/pool ratio
            "score_gain": jnp.mean(s_last) / jnp.maximum(
                jnp.mean(s_first), jnp.finfo(jnp.float32).tiny),
            "ascent_steps": jnp.asarray(self.n_steps, jnp.float32),
        }
        return ResampleSwap(X, idx, kept, stats)

    def redraw(self, params, X_cur, epoch: int) -> ResampleSwap:
        """Dispatch one ascent redraw (async — returns device futures)."""
        return self._redraw_jit(params, X_cur, jnp.asarray(int(epoch)))

    def lower_redraw(self, params, X_cur):
        """The redraw program's ``Lowered`` (cost analysis without a
        compile) — the score/ascent-pass FLOP pricing hook."""
        return self._redraw_jit.lower(params, X_cur, jnp.asarray(0))


class FamilyResampler:
    """:class:`DeviceResampler` batched over a surrogate-factory MODEL
    axis: per-member pool → score → select as ONE jitted program for the
    whole family (``jax.vmap`` over members), so a 64-member family's
    redraw costs one dispatch, exactly like its training step.

    ``residual_fn(params_m, X_m, theta_m)`` is the per-member residual
    with the family parameter θ as a traced operand — the factory's
    member engine.  Each member draws an independent stratified fresh
    pool (keys decorrelated via ``fold_in(fold_in(seed, epoch),
    member)``), scores ``[its current X_f ; fresh]``, and Gumbel-top-k
    selects its own ``n_f`` points; kept rows carry that member's
    per-point λ through :func:`carry_rows_family`.  The returned
    :class:`ResampleSwap` is stacked: ``X_new [M, n_f, d]``, ``idx`` /
    ``kept`` ``[M, n_f]``, stats ``[M]`` per member.  Calling
    :meth:`redraw` only dispatches (async) — the factory double-buffers
    it behind the next training chunk, the PR 10 pipeline over the model
    axis."""

    pipelined = True

    def __init__(self, residual_fn: Callable, xlimits: np.ndarray,
                 n_f: int, n_members: int, *, pool_factor: int = 4,
                 temp: float = 1.0, uniform_frac: float = 0.1,
                 seed: int = 0):
        self.residual_fn = residual_fn
        # tdq: allow[dtype-discipline] domain limits held in f64 on the HOST; the jitted pool draw casts per-dim bounds to f32 scalars
        self.xlimits = np.asarray(xlimits, np.float64)
        self.n_f = int(n_f)
        self.n_members = int(n_members)
        self.temp = float(temp)
        self.uniform_frac = float(uniform_frac)
        self.seed = int(seed)
        self.n_fresh = max(int(pool_factor) - 1, 1) * self.n_f
        self._redraw_jit = jax.jit(self._redraw_impl)

    def _member_redraw(self, params, X_cur, theta, key):
        k_pool, k_sel = jax.random.split(key)
        fresh = _stratified_pool(k_pool, self.n_fresh, self.xlimits)
        pool = jnp.concatenate([X_cur, fresh], axis=0)
        f = self.residual_fn(params, pool, theta)
        return _score_and_select(pool, f, self.n_f, self.temp,
                                 self.uniform_frac, k_sel, None)

    def _redraw_impl(self, params, X_cur, thetas, epoch):
        base = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        keys = jax.vmap(lambda m: jax.random.fold_in(base, m))(
            jnp.arange(self.n_members))
        return jax.vmap(self._member_redraw)(params, X_cur, thetas, keys)

    def redraw(self, params, X_cur, thetas, epoch: int) -> ResampleSwap:
        """Dispatch one family redraw (async — returns device futures,
        stacked along the model axis)."""
        return self._redraw_jit(params, X_cur, thetas,
                                jnp.asarray(int(epoch)))

    def lower_redraw(self, params, X_cur, thetas):
        """The family redraw's ``Lowered`` (cost analysis, no compile)."""
        return self._redraw_jit.lower(params, X_cur, thetas,
                                      jnp.asarray(0))


class FamilyAscentResampler:
    """:class:`AscentResampler` batched over the surrogate-factory MODEL
    axis: every member moves its own collocation set up its own residual
    landscape (θ is a traced operand of the member residual), all members
    in ONE jitted program via ``jax.vmap`` — one dispatch per redraw,
    exactly like the family training step.  Fresh draws are decorrelated
    per member via ``fold_in(fold_in(seed, epoch), member)``; the stacked
    :class:`ResampleSwap` matches :class:`FamilyResampler`'s layout
    (``X_new [M, n_f, d]``, ``idx``/``kept`` ``[M, n_f]``, stats per
    member), so :func:`carry_rows_family` carries λ unchanged."""

    pipelined = True

    def __init__(self, residual_fn: Callable, xlimits: np.ndarray,
                 n_f: int, n_members: int, *, n_steps: int = 5,
                 step_frac: float = 0.005, fresh_frac: float = 0.1,
                 seed: int = 0, score_grad_fn: Optional[Callable] = None):
        self.residual_fn = residual_fn
        # tdq: allow[dtype-discipline] domain limits held in f64 on the HOST; the jitted move casts per-dim bounds to f32
        self.xlimits = np.asarray(xlimits, np.float64)
        self.n_f = int(n_f)
        self.n_members = int(n_members)
        self.n_steps = int(n_steps)
        self.step_frac = float(step_frac)
        self.seed = int(seed)
        self.n_fresh = int(round(max(min(float(fresh_frac), 1.0), 0.0)
                                 * self.n_f))
        self.score_grad_fn = score_grad_fn
        self._redraw_jit = jax.jit(self._redraw_impl)

    def _score_grad(self, params, X, theta):
        if self.score_grad_fn is not None:
            return self.score_grad_fn(params, X, theta)

        def total(Xv):
            f = self.residual_fn(params, Xv, theta)
            parts = f if isinstance(f, tuple) else (f,)
            s = None
            for p in parts:
                a = jnp.sum(jnp.square(jnp.reshape(p, (Xv.shape[0], -1))),
                            axis=1)
                s = a if s is None else s + a
            return jnp.sum(s), s

        (_, s), g = jax.value_and_grad(total, has_aux=True)(X)
        return s, g

    def _member_redraw(self, params, X_cur, theta, key):
        X, s_first, s_last = _ascent_move(
            lambda Xv: self._score_grad(params, Xv, theta), X_cur,
            self.xlimits, self.n_steps, self.step_frac)
        n_f = self.n_f
        row = jnp.arange(n_f)
        if self.n_fresh:
            fresh = _stratified_pool(key, self.n_fresh, self.xlimits)
            _, worst = jax.lax.top_k(-s_last, self.n_fresh)
            is_fresh = jnp.zeros((n_f,), bool).at[worst].set(True)
            X = X.at[worst].set(fresh)
            fresh_rank = jnp.cumsum(is_fresh.astype(jnp.int32)) - 1
            idx = jnp.where(is_fresh, n_f + fresh_rank, row)
            kept = ~is_fresh
        else:
            idx, kept = row, jnp.ones((n_f,), bool)
        stats = {
            "kept_fraction": jnp.mean(kept.astype(jnp.float32)),
            "score_gain": jnp.mean(s_last) / jnp.maximum(
                jnp.mean(s_first), jnp.finfo(jnp.float32).tiny),
            "ascent_steps": jnp.asarray(self.n_steps, jnp.float32),
        }
        return ResampleSwap(X, idx, kept, stats)

    def _redraw_impl(self, params, X_cur, thetas, epoch):
        base = jax.random.fold_in(jax.random.PRNGKey(self.seed), epoch)
        keys = jax.vmap(lambda m: jax.random.fold_in(base, m))(
            jnp.arange(self.n_members))
        return jax.vmap(self._member_redraw)(params, X_cur, thetas, keys)

    def redraw(self, params, X_cur, thetas, epoch: int) -> ResampleSwap:
        """Dispatch one family ascent redraw (async, stacked on the
        model axis)."""
        return self._redraw_jit(params, X_cur, thetas,
                                jnp.asarray(int(epoch)))

    def lower_redraw(self, params, X_cur, thetas):
        """The family redraw's ``Lowered`` (cost analysis, no compile)."""
        return self._redraw_jit.lower(params, X_cur, thetas,
                                      jnp.asarray(0))


def _carry_impl(rows, idx, kept, fresh_zero: bool, placement):
    n_f = rows.shape[0]
    g = jnp.take(rows, jnp.clip(idx, 0, n_f - 1), axis=0)
    k = kept.reshape((-1,) + (1,) * (g.ndim - 1))
    if fresh_zero:
        fresh0 = jnp.zeros(g.shape[1:], g.dtype)
    else:
        n_kept = jnp.sum(kept)
        mean_kept = (jnp.sum(jnp.where(k, g, 0.0), axis=0)
                     / jnp.maximum(n_kept, 1).astype(g.dtype))
        # adaptive SA-λ schedule (arXiv:2207.04084): fresh rows enter at
        # the carried distribution's CURRENT mean — the self-supervision
        # weight level training has adapted to — not the cold-start init
        # (degenerate all-fresh redraw: the old set's mean)
        fresh0 = jnp.where(n_kept > 0, mean_kept, jnp.mean(rows, axis=0))
    new = jnp.where(k, g, fresh0)
    mean_old = jnp.mean(rows)
    drift = jnp.abs(jnp.mean(new) - mean_old) / jnp.maximum(
        jnp.abs(mean_old), jnp.finfo(jnp.float32).tiny)
    if placement is not None:
        new = jax.lax.with_sharding_constraint(new, placement)
    return new, drift


_carry_jit = jax.jit(_carry_impl,
                     static_argnames=("fresh_zero", "placement"))


def carry_rows(rows, idx, kept, fresh_zero: bool = False):
    """Carry per-point state through a :class:`DeviceResampler` redraw.

    ``rows`` is any ``[n_f, ...]`` array row-aligned with the OLD
    collocation set (per-point SA λ, or its λ-ascent Adam moments).  Kept
    pool rows gather their trained values; fresh rows initialize at the
    carried distribution's mean (``fresh_zero=True``: at zero — the
    optimizer-moment rule: fresh points have no ascent history).  Runs
    jitted so multi-host sharded λ never transit the host; the output
    keeps the input's mesh sharding.  Returns ``(new_rows, drift)`` where
    ``drift`` is the relative change of the mean — the λ-drift gauge."""
    placement = getattr(rows, "sharding", None)
    if placement is None or getattr(placement, "mesh", None) is None:
        placement = None
    return _carry_jit(rows, idx, kept, fresh_zero, placement)


def _carry_family_impl(rows, idx, kept, fresh_zero: bool):
    return jax.vmap(
        lambda r, i, k: _carry_impl(r, i, k, fresh_zero, None))(
            rows, idx, kept)


_carry_family_jit = jax.jit(_carry_family_impl,
                            static_argnames=("fresh_zero",))


def carry_rows_family(rows, idx, kept, fresh_zero: bool = False):
    """:func:`carry_rows` batched over the surrogate-factory model axis:
    ``rows [M, n_f, ...]`` row-aligned with each member's OLD collocation
    set, gathered through that member's ``idx [M, n_f]`` lane.  Returns
    ``(new_rows, drift)`` with ``drift [M]`` per member."""
    return _carry_family_jit(rows, idx, kept, fresh_zero)


def gather_rows_multihost(X_global) -> np.ndarray:
    """Full host copy of a multi-process sharded ``[N, d]`` array: each
    process reads its addressable row slices and
    :func:`_allgather_by_row` assembles the identical global array
    everywhere (``np.asarray`` on a cross-host array is illegal)."""
    n = int(X_global.shape[0])
    local: dict[int, np.ndarray] = {}
    for shard in X_global.addressable_shards:
        start = shard.index[0].start or 0
        # tdq: allow[dtype-discipline] feeds the f64 row-lane packing contract of _allgather_by_row
        local[start] = np.asarray(shard.data, np.float64)
    out = _allgather_by_row(local, n)
    return out.reshape((n,) + tuple(X_global.shape[1:]))


def make_residual_resampler(residual_fn: Callable, xlimits: np.ndarray,
                            n_f: int, *, pool_factor: int = 4,
                            temp: float = 1.0, uniform_frac: float = 0.1,
                            seed: int = 0,
                            like=None) -> Callable:
    """Build ``resample(params, epoch) -> X_new`` for the fit loop.

    ``like``: an existing (possibly sharded) collocation array — the fresh
    pool and the selected points are placed with its sharding so the redraw
    is transparent to a ``dist=True`` compiled step.  Each call uses a
    different pool seed (``seed + epoch``) so successive redraws explore."""
    placement = getattr(like, "sharding", None)
    n_pool = max(int(pool_factor) * n_f, n_f)
    if placement is not None and getattr(placement, "mesh", None) is not None:
        n_dev = int(np.prod(placement.mesh.devices.shape))
        # fail at build time, not mid-training: the selected X_new has n_f
        # rows and must device_put onto the mesh, so n_f itself (not just
        # the pool) has to shard evenly (advisor finding, round 2 — the
        # earlier fix only rounded the pool and moved the shape error two
        # lines down).  n_pool = pool_factor*n_f is then divisible too.
        if n_f % n_dev:
            raise ValueError(
                f"n_f={n_f} must be divisible by the mesh device count "
                f"{n_dev} for resampling under dist=True")
    assert n_pool >= n_f, (n_pool, n_f)

    multihost = jax.process_count() > 1
    if multihost and placement is None:
        raise ValueError(
            "multi-host resampling needs a sharded `like` array so the "
            "fresh pool can be placed on the global mesh")

    def _place(arr_np):
        """float32 device array with the training placement.  Multi-host:
        every process holds the identical numpy array, so assembling the
        global array from per-shard row slices is consistent."""
        arr_np = np.asarray(arr_np, np.float32)
        if multihost:
            return jax.make_array_from_callback(
                arr_np.shape, placement, lambda idx: arr_np[idx])
        out = jnp.asarray(arr_np)
        return jax.device_put(out, placement) if placement is not None else out

    def resample(params, epoch: int) -> jnp.ndarray:
        # two decorrelated streams per redraw (pool LHS vs selection noise),
        # both keyed on (seed, epoch) so distinct epochs explore new pools —
        # and therefore identical on every process of a multi-host mesh
        pool_ss, sel_ss = np.random.SeedSequence([seed, int(epoch)]).spawn(2)
        pool = LatinHypercubeSample(n_pool, xlimits, criterion="c",
                                    seed=int(pool_ss.generate_state(1)[0]))
        pool_j = _place(pool)
        if multihost:
            scores = _scores_multihost(residual_fn, params, pool_j, n_pool)
        else:
            scores = residual_scores(residual_fn, params, pool_j)
        rng = np.random.default_rng(sel_ss)
        idx = importance_select(scores, n_f, temp=temp,
                                uniform_frac=uniform_frac, rng=rng)
        X_np = np.asarray(pool[np.sort(idx)], np.float32)
        # host copy for callers that must read the live set without touching
        # the device array (NTK subsample on multi-process meshes) —
        # identical on every process by seed determinism
        resample.last_host = X_np
        return _place(X_np)

    return resample
