"""Compute primitives: losses, derivative combinators, mesh builders."""

from .derivatives import (UFn, d, grad, laplacian, make_ufn,  # noqa: F401
                          set_default_grad_mode, vmap_residual)
from .losses import MSE, default_g, g_MSE, relative_l2  # noqa: F401
from .meshes import flatten_and_stack, grid_points, multimesh  # noqa: F401
from .resampling import (importance_select,  # noqa: F401
                         make_residual_resampler, residual_scores)
