"""The residual-authoring engine — the JAX-idiomatic heart of the framework.

The reference's user contract is a PDE residual written against a *batched*
network with ``tf.gradients`` over input columns (``examples/burgers-new.py:26-32``,
consumed at ``models.py:187``).  The TPU-native contract replaces this with a
**scalar point function**: the user writes the residual at a single point
``(x, t, ...)`` using ``jax.grad``-based combinators, and the framework vmaps
it over collocation points and jits the whole thing.  Per-point closed-form
gradients + ``vmap`` is exactly the shape XLA fuses best on TPU: one traced
point program → one batched kernel on the MXU, no dynamic shapes.

User-facing example (Burgers)::

    from tensordiffeq_tpu import grad

    def f_model(u, x, t):
        u_x  = grad(u, "x")
        u_xx = grad(u_x, "x")
        u_t  = grad(u, "t")
        return u_t(x, t) + u(x, t) * u_x(x, t) - nu * u_xx(x, t)

``u`` is a :class:`UFn`: a callable ``u(*coords) -> scalar`` carrying its
coordinate names, so derivatives can be requested by name or index.  Vector
outputs are accessed by component: ``u[0]``, ``u[1]`` are scalar ``UFn``s
(covers the reference's multi-output residual tuple case, ``models.py:189-191``).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp


class UFn:
    """A scalar (or vector) point function with named coordinates.

    Wraps ``fn(*coords) -> scalar | vector`` and records ``varnames`` so that
    :func:`grad` can resolve derivative directions by name.
    """

    def __init__(self, fn: Callable, varnames: Sequence[str],
                 n_out: int = 1):
        self._fn = fn
        self.varnames = tuple(varnames)
        self.n_out = n_out

    def __call__(self, *coords):
        return self._fn(*coords)

    def __getitem__(self, k: int) -> "UFn":
        """Scalar component ``u[k]`` of a vector-valued point function."""
        if self.n_out == 1:
            if k != 0:
                raise IndexError("scalar UFn only has component 0")
            return self
        return UFn(lambda *c: self._fn(*c)[k], self.varnames, n_out=1)

    def differentiate(self, num: int, mode: str) -> "UFn":
        """Derivative along coordinate position ``num`` (used by
        :func:`grad`; symbolic subclasses override this)."""
        dfn = (_directional(self._fn, num) if mode == "fwd"
               else jax.grad(self._fn, argnums=num))
        return UFn(dfn, self.varnames, n_out=1)

    def argnum(self, var: Union[str, int]) -> int:
        if isinstance(var, int):
            return var
        try:
            return self.varnames.index(var)
        except ValueError:
            raise ValueError(
                f"Unknown variable {var!r}; this function has coordinates "
                f"{self.varnames}") from None


# Coordinate derivatives default to forward mode: a PINN residual
# differentiates a scalar point function along ONE of a handful of input
# coordinates, which is exactly the shape where a jvp sweep beats building
# and transposing a reverse-mode graph — and nested grads become
# jvp-over-jvp instead of reverse-over-reverse.  (The outer loss gradient
# w.r.t. the network *parameters* is still reverse-mode; reverse-over-forward
# composes cleanly.)  Measured ~8% faster end-to-end on the AC SA train step
# on a v5e chip vs the reverse-mode chain.
_DEFAULT_MODE = "fwd"


def set_default_grad_mode(mode: str) -> None:
    """Set the global derivative mode for :func:`grad`: ``"fwd"`` (jvp
    sweeps, default) or ``"rev"`` (``jax.grad`` chains)."""
    global _DEFAULT_MODE
    if mode not in ("fwd", "rev"):
        raise ValueError(f"grad mode must be 'fwd' or 'rev', got {mode!r}")
    _DEFAULT_MODE = mode


def _directional(fn: Callable, num: int) -> Callable:
    """Forward-mode partial derivative of ``fn`` along argument ``num``."""

    def dfn(*coords):
        coords = tuple(jnp.asarray(c) for c in coords)
        tangents = tuple(
            jnp.ones_like(c) if i == num else jnp.zeros_like(c)
            for i, c in enumerate(coords))
        _, tang = jax.jvp(fn, coords, tangents)
        if jnp.ndim(tang) != 0:
            # jax.grad would raise here; keep the same contract in fwd mode
            raise TypeError(
                "grad() requires a scalar-output function, got output shape "
                f"{jnp.shape(tang)}; select a component first (u[k]) or set "
                "n_out on the UFn")
        return tang

    return dfn


def grad(u: Union[UFn, Callable], var: Union[str, int] = 0,
         mode: Optional[str] = None) -> UFn:
    """Derivative of a scalar point function along coordinate ``var``.

    ``var`` may be a coordinate name (``"x"``) when ``u`` is a :class:`UFn`,
    or a positional index.  Nested freely for higher orders:
    ``grad(grad(u, "x"), "x")`` is ``u_xx``.  ``mode`` overrides the global
    default ("fwd" jvp sweep / "rev" ``jax.grad``) per call.
    """
    mode = mode or _DEFAULT_MODE
    if isinstance(u, UFn):
        if u.n_out != 1:
            raise ValueError(
                "grad() needs a scalar function; select a component first, "
                "e.g. grad(u[0], 'x')")
        return u.differentiate(u.argnum(var), mode)
    if not isinstance(var, int):
        raise ValueError("grad(fn, 'name') requires a UFn; pass an int argnum")
    dfn = _directional(u, var) if mode == "fwd" else jax.grad(u, argnums=var)
    return UFn(dfn, varnames=(), n_out=1)


def d(u: UFn, var: Union[str, int], order: int = 1) -> UFn:
    """``order``-th derivative along one coordinate: ``d(u, 'x', 2)`` = u_xx."""
    out = u
    for _ in range(order):
        out = grad(out, var)
    return out


def laplacian(u: UFn, spatial_vars: Optional[Sequence[Union[str, int]]] = None) -> UFn:
    """Sum of unmixed second derivatives over ``spatial_vars`` (default: all
    coordinates).  Common enough in the reference examples (Helmholtz/Poisson
    steady state, ``examples/steady-state.py``) to deserve a combinator."""
    names = spatial_vars if spatial_vars is not None else range(len(u.varnames))
    terms = [d(u, v, 2) for v in names]
    return UFn(lambda *c: sum(t(*c) for t in terms), u.varnames, n_out=1)


def make_ufn(apply_fn: Callable, params, varnames: Sequence[str],
             n_out: int = 1) -> UFn:
    """Bind a Flax-style ``apply_fn(params, x[d]) -> y[n_out]`` into a
    per-point :class:`UFn` over scalar coordinates.

    This is the bridge the solver uses: the batched network becomes a scalar
    point function, derivatives are exact per-point ``jax.grad`` chains, and
    the whole residual is later ``vmap``-ed back over the point batch (the
    TPU-native replacement for ``tf.gradients`` on column tensors,
    reference ``models.py:63,187``).
    """
    def u_point(*coords):
        x = jnp.stack([jnp.asarray(c, dtype=jnp.float32) for c in coords])
        out = apply_fn(params, x)
        return out[0] if n_out == 1 else out

    return UFn(u_point, varnames, n_out=n_out)


def vmap_residual(f_model: Callable, u: UFn, n_coords: int) -> Callable:
    """Turn a per-point residual ``f_model(u, *coords)`` into a batched
    function over an ``[N, n_coords]`` point matrix.

    Returns ``residual(X) -> [N] | tuple of [N]`` (tuples for multi-equation
    systems, mirroring reference ``models.py:189-191``).
    """
    def per_point(pt):
        coords = tuple(pt[i] for i in range(n_coords))
        return f_model(u, *coords)

    return jax.vmap(per_point)
