"""NTK-based adaptive loss weighting (Wang, Yu & Perdikaris,
arXiv:2007.14527 — "When and why PINNs fail to train: an NTK perspective").

The reference *declares* this method — ``Adaptive_type = 3`` maps to
"Neural Tangent Kernel based adaptive methods" (``models.py:39``) — but
never implements it: type 3 just sets ``weight_outside_sum=True,
isAdaptive=False`` and the NTK branch is dead code (``models.py:76-84``,
SURVEY §2.3).  This module is the real thing.

Method.  For loss terms ``L_i`` with per-point errors ``e_i(θ)``, the NTK of
term i is ``K_i = J_i J_iᵀ`` with ``J_i = ∂e_i/∂θ``.  The balanced weights

    λ_i = (Σ_j tr K_j) / tr K_i

equalise the terms' effective convergence rates (eq. 6.1 of the paper).
``tr K_i = ‖J_i‖_F²`` — no NxN kernel is ever materialised; we take the
Frobenius norm of the per-term Jacobian over a fixed subsample of points.
Weights are recomputed every few hundred steps OUTSIDE the jitted training
scan and enter the loss as frozen scalar multipliers (SA type-2 position).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from ..boundaries import BC
from .derivatives import make_ufn, vmap_residual


def _subsample(arr: jnp.ndarray, n: Optional[int]) -> jnp.ndarray:
    """Deterministic stride subsample of the leading axis to ≤ n rows."""
    if n is None or arr.shape[0] <= n:
        return arr
    idx = jnp.linspace(0, arr.shape[0] - 1, n).astype(jnp.int32)
    return arr[idx]


def residual_subsample(X_f, max_points: int = 256) -> jnp.ndarray:
    """The residual-term evaluation points for the NTK traces: the same
    deterministic stride subsample ``build_error_fns`` takes at build time,
    computable from the *current* collocation set — so callers whose ``X_f``
    changes during training (adaptive resampling, dist trimming) can keep the
    traces aligned with the points actually being trained.

    Subsample-size sensitivity (measured 2026-08-01, Helmholtz
    ``runs/ntk_sensitivity.json``): the λ balance the traces produce is
    identical to <0.1% across ``max_points`` 256/512/1024 (λ_res 1.0010 /
    1.0008 / 1.0004; λ_BC ≈ 100.1 all three) and the final rel-L2 stays in
    the config's normal band — the 256-point default is not a distorting
    factor, it just bounds the trace cost."""
    return _subsample(jnp.asarray(X_f, jnp.float32), max_points)


def build_error_fns(apply_fn: Callable, varnames: Sequence[str], n_out: int,
                    f_model: Callable, bcs: Sequence[BC], X_f: jnp.ndarray,
                    n_residuals: int, max_points: int = 256,
                    data_X=None, data_s=None):
    """Per-term error functions ``e(params) -> [m]`` on fixed subsampled
    points, mirroring the term order of
    :func:`tensordiffeq_tpu.models.assembly.build_loss_fn`.

    Returns ``(bc_fns, res_fns, data_fn)`` — ``data_fn`` is ``None`` when no
    assimilation data is registered.
    """
    ndim = len(varnames)

    def vderiv(dfn, params, pts):
        u = make_ufn(apply_fn, params, varnames, n_out)
        out = jax.vmap(lambda pt: dfn(u, *(pt[i] for i in range(ndim))))(pts)
        return out if isinstance(out, tuple) else (out,)

    bc_fns = []
    for bc in bcs:
        if bc.isPeriodic:
            uppers = [_subsample(jnp.asarray(p, jnp.float32), max_points)
                      for p in bc.upper]
            lowers = [_subsample(jnp.asarray(p, jnp.float32), max_points)
                      for p in bc.lower]
            derivs = list(bc.deriv_model)

            def e_periodic(params, uppers=uppers, lowers=lowers, derivs=derivs):
                outs = []
                for up_pts, lo_pts, dfn in zip(uppers, lowers, derivs):
                    ups = vderiv(dfn, params, up_pts)
                    los = vderiv(dfn, params, lo_pts)
                    outs += [(a - b).ravel() for a, b in zip(ups, los)]
                return jnp.concatenate(outs)

            bc_fns.append(e_periodic)
        elif bc.isNeumann:
            inputs = [_subsample(jnp.asarray(p, jnp.float32), max_points)
                      for p in bc.input]
            vals = [_subsample(jnp.asarray(v, jnp.float32), max_points)
                    for v in bc.val]
            derivs = list(bc.deriv_model)

            def e_neumann(params, inputs=inputs, vals=vals, derivs=derivs):
                outs = []
                for pts, val, dfn in zip(inputs, vals, derivs):
                    for comp in vderiv(dfn, params, pts):
                        outs.append((comp.reshape(val.shape) - val).ravel())
                return jnp.concatenate(outs)

            bc_fns.append(e_neumann)
        else:  # value-type (IC / Dirichlet)
            pts = jnp.asarray(bc.input, jnp.float32)
            val = jnp.asarray(bc.val, jnp.float32)
            k = min(pts.shape[0], max_points) if max_points else pts.shape[0]
            pts, val = _subsample(pts, k), _subsample(val, k)

            def e_value(params, pts=pts, val=val):
                return (apply_fn(params, pts) - val).ravel()

            bc_fns.append(e_value)

    X_sub0 = residual_subsample(X_f, max_points)

    def res_all_fn(params, X_sub=None):
        """All residual components stacked as ``[n_residuals, m]`` — one
        forward + one Jacobian pass covers every equation of a system.

        ``X_sub`` overrides the build-time subsample (pass
        :func:`residual_subsample` of the live collocation set when it can
        change during training)."""
        pts = X_sub0 if X_sub is None else X_sub
        u = make_ufn(apply_fn, params, varnames, n_out)
        out = vmap_residual(f_model, u, ndim)(pts)
        out = out if isinstance(out, tuple) else (out,)
        assert len(out) == n_residuals, (len(out), n_residuals)
        return jnp.stack([o.ravel() for o in out])

    data_fn = None
    if data_X is not None:
        dX = _subsample(jnp.asarray(data_X, jnp.float32), max_points)
        ds = _subsample(jnp.asarray(data_s, jnp.float32), max_points)

        def data_fn(params):
            return (apply_fn(params, dX) - ds).ravel()

    return bc_fns, res_all_fn, data_fn


def trace_K(e_fn: Callable, params) -> jnp.ndarray:
    """``tr(J Jᵀ) = ‖∂e/∂θ‖_F²`` for one loss term."""
    J = jax.jacrev(e_fn)(params)
    return sum(jnp.sum(jnp.square(leaf))
               for leaf in jax.tree_util.tree_leaves(J))


def make_ntk_weight_fn(bc_fns, res_all_fn, n_residuals: int, data_fn=None,
                       eps: float = 1e-12,
                       max_ratio: Optional[float] = None) -> Callable:
    """Build the jitted weight-update function
    ``ntk_weights(params[, X_sub]) -> {"BCs": [...], "residual": [...][, "data": [...]]}``
    with each weight a 0-d scalar array λ_i = Σ tr K / tr K_i, matching the
    lambdas pytree the solver trains (the optional ``"data"`` entry weights
    the assimilation term).  ``X_sub`` re-points the residual traces at the
    current collocation subsample (see :func:`residual_subsample`) so the
    balance follows adaptive resampling.

    ``max_ratio`` bounds the weights' dynamic range: every λ is clipped to
    ``max_ratio × min(λ)`` (uncapped terms keep the paper-exact
    ``λ_i·tr K_i = Σ tr K`` invariant).  Measured necessity, round 4: on
    Helmholtz with a high-frequency forcing the raw formula assigns the
    (second-derivative-amplified, large-trace) residual term ~4.5e3× LESS
    weight than the boundary terms — Adam's update direction is then
    essentially BC-only, the network fits u≈0 (all BCs are zero) and the
    PDE is never solved (rel-L2 1.4 vs 7.3e-2 for the unweighted control,
    `runs/ntk_helmholtz_uncapped.json`).  A bounded range keeps the
    balancing direction while no term starves."""

    @jax.jit
    def ntk_weights(params, X_sub=None):
        bc_traces = [trace_K(f, params) for f in bc_fns]
        # one Jacobian of the stacked [n_res, m] residual matrix; per-row
        # Frobenius norms give every equation's trace in a single pass
        res_fn = (res_all_fn if X_sub is None
                  else (lambda p: res_all_fn(p, X_sub)))
        J = jax.jacrev(res_fn)(params)
        res_traces_vec = sum(
            jnp.sum(jnp.square(leaf), axis=tuple(range(1, leaf.ndim)))
            for leaf in jax.tree_util.tree_leaves(J))
        res_traces = [res_traces_vec[j] for j in range(n_residuals)]
        data_traces = [trace_K(data_fn, params)] if data_fn else []
        traces = bc_traces + res_traces + data_traces
        total = sum(traces)
        lam = [(total / (t + eps)).reshape(()) for t in traces]
        if max_ratio is not None:
            lam_min = jnp.min(jnp.stack(lam))
            lam = [jnp.minimum(l, max_ratio * lam_min) for l in lam]
        n_bc = len(bc_fns)
        out = {"BCs": lam[:n_bc],
               "residual": lam[n_bc:n_bc + n_residuals]}
        if data_fn:
            out["data"] = [lam[-1]]
        return out

    return ntk_weights
