"""Stacked Taylor-mode derivative propagation for tanh MLPs.

The generic residual path evaluates the user's ``f_model`` with per-point
``jvp``/``grad`` chains: every requested derivative re-traverses the network.
This module instead pushes ONE wavefront through the MLP that carries the
primal together with every requested directional derivative — first, second,
arbitrary (mixed) third, and unmixed fourth order: per layer, all channels
share a single batched matmul (``[C, N, w]``, channels on a fresh leading
axis so the point axis keeps its dist-training sharding) and the tanh
derivative chain ``d1 = 1-z², d2 = -2·z·d1, d3 = -2·d1·(1-3z²),
d4 = -2·d2·(1-3z²) + 12·z·d1²`` is applied elementwise (VPU, fused by XLA).
The higher orders use the collapsing recurrence of Collapsing Taylor Mode AD
(arXiv:2505.13644): instead of re-traversing the network once per order
(nested ``jacfwd`` towers), each layer advances every order of the wavefront
interleaved — the order-k channel of the post-activation is a Faà di Bruno
combination of the *already-propagated* lower-order channels of the same
layer, so a fourth derivative costs one extra channel in the shared matmul,
not a fourth traversal.  Reverse-mode AD composes through it for the loss
gradient, so no custom VJP is required for correctness.

This replaces, for the standard MLP family, the repeated network traversals
of the combinator path (reference contract: batched ``tf.gradients`` over
input columns, ``tensordiffeq/models.py:187``); arbitrary networks and
higher-order requests fall back to the generic engine.

Derivative requests are canonical multi-indices: sorted tuples of coordinate
positions, e.g. ``()`` primal, ``(0,)`` = u_x, ``(0, 1)`` = u_xt,
``(0, 0, 1)`` = u_xxt, ``(0, 0, 0, 0)`` = u_xxxx.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

MultiIndex = tuple  # sorted tuple of coordinate indices


def canonical(idx: Sequence[int]) -> MultiIndex:
    """Canonical (sorted) multi-index — mixed partials commute for the smooth
    networks we differentiate."""
    return tuple(sorted(idx))


def supported(idx: Sequence[int]) -> bool:
    """Orders handled by the propagation: everything to 3rd order (mixed
    included — KS/Burgers-type ``u_xxt``), plus unmixed 4th order (beam /
    Kuramoto–Sivashinsky ``u_xxxx``)."""
    idx = canonical(idx)
    if len(idx) <= 3:
        return True
    return len(idx) == 4 and len(set(idx)) == 1


def closure(requests: set) -> tuple[list, list, list, list]:
    """Ingredient closure: propagate every channel a requested derivative
    needs (each order's Faà di Bruno recurrence consumes every lower-order
    channel over the same index subsets).  Returns
    ``(firsts, seconds, thirds, fourths)`` as sorted canonical lists."""
    firsts, seconds, thirds, fourths = set(), set(), set(), set()
    for idx in requests:
        idx = canonical(idx)
        if len(idx) == 1:
            firsts.add(idx)
        elif len(idx) == 2:
            seconds.add(idx)
        elif len(idx) == 3:
            thirds.add(idx)
        elif len(idx) == 4:
            fourths.add(idx)
    for (k, _, _, _) in fourths:  # unmixed: one lower-order chain
        thirds.add((k, k, k))
    for (i, j, k) in thirds:  # all pairwise seconds feed the recurrence
        seconds.update({canonical((i, j)), canonical((i, k)),
                        canonical((j, k))})
    for (i, j) in seconds:
        firsts.update({(i,), (j,)})
    return sorted(firsts), sorted(seconds), sorted(thirds), sorted(fourths)


def extract_mlp_layers(params) -> Optional[list]:
    """Pull ``[(W, b), ...]`` out of a Flax :class:`~..networks.MLP` param
    tree (``Dense_0..Dense_k``); ``None`` if the structure doesn't match."""
    try:
        inner = params["params"]
        layers = []
        for i in range(len(inner)):
            d = inner[f"Dense_{i}"]
            layers.append((d["kernel"], d["bias"]))
        return layers
    except (KeyError, TypeError):
        return None


def taylor_derivatives(layers: list, X: jnp.ndarray, requests: set,
                       precision=None, flat_matmul: bool = False,
                       compute_dtype=None) -> dict:
    """Evaluate the MLP and all ``requests`` derivatives in one propagation.

    Args:
      layers: ``[(W [in, out], b [out]), ...]``; tanh between layers, linear
        head (the :class:`~tensordiffeq_tpu.networks.MLP` family).
      X: ``[N, d]`` evaluation points.
      requests: set of canonical multi-indices (see :func:`supported`).
      precision: matmul precision (pass the network's, e.g. ``HIGHEST``, for
        bit-comparable values with the plain forward pass).
      flat_matmul: collapse the channel stack into the point axis for each
        layer matmul (``[C·N, in] @ W`` instead of the batched
        ``[C, N, in] @ W``).  The pallas kernel body needs this: the batched
        form's weight-cotangent transpose is a double contraction Mosaic's
        ``tpu.matmul`` cannot lower.  Keep ``False`` outside kernels — the
        reshape would cross a GSPMD-sharded point axis under ``dist=True``.
      compute_dtype: mixed-precision matmul inputs (e.g. ``jnp.bfloat16``):
        the layer matmuls cast their operands to this dtype and accumulate
        in float32 (``preferred_element_type``), putting the MXU's native
        single-pass bf16 path under the propagation; every pointwise op
        (tanh chain rules, channel products) stays float32.  ``None`` keeps
        full-precision matmuls governed by ``precision``.  An accuracy
        trade-off the caller must opt into — derivatives through tanh are
        precision-sensitive.

    Returns ``{multi_index: [N, n_out] array}`` including the primal ``()``.
    """
    X = jnp.asarray(X)
    N, d = X.shape
    firsts, seconds, thirds, fourths = closure(set(map(canonical, requests)))

    # Channel wavefront. Z primal; T/S/U/F keyed by canonical multi-index.
    # Channels stack on a NEW leading axis: the point axis keeps its
    # position (and, under dist training, its sharding — stacking along the
    # sharded axis would make GSPMD gather the batch at every layer).
    Z = X
    # one-hot via iota-compare, not .at[].set(): scatter has no Mosaic
    # lowering, and this code also runs inside the pallas kernel body
    col = jax.lax.broadcasted_iota(jnp.int32, X.shape, 1)
    T = {idx: jnp.where(col == idx[0], 1.0, 0.0).astype(X.dtype)
         for idx in firsts}
    S = {idx: jnp.zeros_like(X) for idx in seconds}
    U = {idx: jnp.zeros_like(X) for idx in thirds}
    F = {idx: jnp.zeros_like(X) for idx in fourths}

    order = [("z", ())] + [("t", i) for i in firsts] + \
            [("s", i) for i in seconds] + [("u", i) for i in thirds] + \
            [("f", i) for i in fourths]

    n_layers = len(layers)
    for li, (W, b) in enumerate(layers):
        stacked = jnp.stack(
            [Z] + [T[i] for i in firsts] + [S[i] for i in seconds]
            + [U[i] for i in thirds] + [F[i] for i in fourths],
            axis=0)  # [C, N, w_in]
        # one (batched) MXU matmul for every channel
        if compute_dtype is not None:
            lhs, rhs = stacked.astype(compute_dtype), W.astype(compute_dtype)
            mm = partial(jnp.matmul, preferred_element_type=jnp.float32)
        else:
            lhs, rhs = stacked, W
            mm = partial(jnp.matmul, precision=precision)
        if flat_matmul:
            C = lhs.shape[0]
            out = mm(lhs.reshape(C * N, -1), rhs).reshape(C, N, -1)
        else:
            out = mm(lhs, rhs)
        chunks = dict(zip(order, out))
        P = chunks[("z", ())] + b
        Q = {i: chunks[("t", i)] for i in firsts}
        R = {i: chunks[("s", i)] for i in seconds}
        V = {i: chunks[("u", i)] for i in thirds}
        G = {i: chunks[("f", i)] for i in fourths}

        if li == n_layers - 1:  # linear head: channels pass through
            Z, T, S, U, F = P, Q, R, V, G
            break

        Z = jnp.tanh(P)
        d1 = 1.0 - Z * Z
        d2 = -2.0 * Z * d1
        d3 = -2.0 * d1 * (1.0 - 3.0 * Z * Z)
        T = {i: d1 * Q[i] for i in firsts}
        S = {(i, j): d1 * R[(i, j)] + d2 * Q[(i,)] * Q[(j,)]
             for (i, j) in seconds}

        def q(k):
            return Q[(k,)]

        def r(i, j):
            return R[canonical((i, j))]

        # Faà di Bruno, third order over directions (i, j, k) — repeated
        # indices included (i=j=k collapses to the classic unmixed chain
        # d3·g'³ + 3·d2·g'·g'' + d1·g'''):
        # (tanh∘g)_ijk = d3·gᵢgⱼg_k + d2·(g_ij·g_k + g_ik·g_j + g_jk·g_i)
        #               + d1·g_ijk
        U = {(i, j, k): (d3 * q(i) * q(j) * q(k)
                         + d2 * (r(i, j) * q(k) + r(i, k) * q(j)
                                 + r(j, k) * q(i))
                         + d1 * V[(i, j, k)])
             for (i, j, k) in thirds}
        if fourths:
            # fourth derivative of tanh, continuing the d-chain
            d4 = -2.0 * d2 * (1.0 - 3.0 * Z * Z) + 12.0 * Z * d1 * d1
            # unmixed fourth order along k (Faà di Bruno over the
            # partitions of a 4-set: {4}, {3,1}×4, {2,2}×3, {2,1,1}×6,
            # {1,1,1,1}):
            # (tanh∘g)_kkkk = d1·g_kkkk + 4·d2·g_kkk·g_k + 3·d2·g_kk²
            #                + 6·d3·g_kk·g_k² + d4·g_k⁴
            F = {(k, _k2, _k3, _k4): (d1 * G[(k, k, k, k)]
                                      + 4.0 * d2 * V[(k, k, k)] * q(k)
                                      + 3.0 * d2 * r(k, k) * r(k, k)
                                      + 6.0 * d3 * r(k, k) * q(k) * q(k)
                                      + d4 * q(k) ** 4)
                 for (k, _k2, _k3, _k4) in fourths}
        else:
            F = {}

    table = {(): Z}
    table.update({i: T[i] for i in firsts})
    table.update({i: S[i] for i in seconds})
    table.update({i: U[i] for i in thirds})
    table.update({i: F[i] for i in fourths})
    return table
