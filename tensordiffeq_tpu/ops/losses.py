"""Loss primitives for collocation training.

TPU-native re-design of the reference's weighted-MSE family
(``tensordiffeq/utils.py:38-48``).  All functions are pure, jit-safe and
dtype-preserving; they operate on arrays of any shape and reduce with a full
mean, exactly matching the reference semantics:

* ``MSE(pred, actual)``                     -> ``mean((pred-actual)**2)``
* ``MSE(..., weights, outside_sum=False)``  -> ``mean((w*(pred-actual))**2)``
  (the SA-PINN "type 1" per-point weighting, McClenny et al. arXiv:2009.04544)
* ``MSE(..., weights, outside_sum=True)``   -> ``w * mean((pred-actual)**2)``
  ("type 2" scalar per-loss weighting)
* ``g_MSE(pred, actual, g_lam)``            -> ``mean(g_lam*(pred-actual)**2)``

For distributed training the mean is computed locally per shard; under
``jax.jit`` over a :class:`jax.sharding.Mesh` XLA inserts the cross-device
reduction automatically, so these stay backend-agnostic.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp


def MSE(pred, actual=0.0, weights: Optional[jnp.ndarray] = None,
        outside_sum: bool = False):
    """Weighted mean-squared error (reference: ``utils.py:38-44``)."""
    diff = pred - actual
    if weights is not None:
        if outside_sum:
            return weights * jnp.mean(jnp.square(diff))
        return jnp.mean(jnp.square(weights * diff))
    return jnp.mean(jnp.square(diff))


def g_MSE(pred, actual, g_lam):
    """MSE with a multiplicative weight *inside* the mean but *outside* the
    square (reference: ``utils.py:47-48``): ``mean(g_lam * (pred-actual)**2)``.
    Used for the optional ``g(lambda)`` transform of SA weights."""
    return jnp.mean(g_lam * jnp.square(pred - actual))


def default_g(lam):
    """Default SA-weight transform ``g(lam) = lam**2`` (the convention used by
    the reference's older API, ``examples/AC-dist.py:89-90``)."""
    return jnp.square(lam)


def causal_residual_loss(sq_errors, t_column, t_bounds, eps: float,
                         n_bins: int):
    """Temporal-causality-weighted residual loss (Wang, Sankaran &
    Perdikaris, arXiv:2203.07404) — beyond-reference.

    Collocation points are binned uniformly along time; bin ``b``'s mean
    squared residual ``L_b`` is weighted by
    ``w_b = exp(-eps * sum_{b' < b} L_b')`` (stop-gradient), so later times
    only start training once earlier times are resolved — the fix for the
    stiff time-evolution failure mode (Allen-Cahn is the paper's flagship
    case).  Returns ``(loss, w_last)``; training is "causally complete"
    when ``w_last -> 1``.

    Pure jax, static shapes: bins come from a ``digitize``-free clip of the
    normalised time column, so the same compiled step serves resampled /
    minibatched / sharded point sets (under a mesh, XLA inserts the
    cross-device reductions for the segment sums).
    """
    t0, t1 = t_bounds
    sq = jnp.reshape(sq_errors, (-1,))
    pos = (jnp.reshape(t_column, (-1,)) - t0) / (t1 - t0)
    bins = jnp.clip((pos * n_bins).astype(jnp.int32), 0, n_bins - 1)
    ones = jnp.ones_like(sq)
    counts = jax.ops.segment_sum(ones, bins, num_segments=n_bins)
    per_bin = jax.ops.segment_sum(sq, bins, num_segments=n_bins) \
        / jnp.maximum(counts, 1.0)
    cum = jnp.concatenate([jnp.zeros((1,), per_bin.dtype),
                           jnp.cumsum(per_bin)[:-1]])
    w = jax.lax.stop_gradient(jnp.exp(-eps * cum))
    return jnp.mean(w * per_bin), w[-1]


def relative_l2(pred, ref):
    """Relative L2 error ``||ref - pred||_2 / ||ref||_2`` — THE accuracy
    metric of every reference example (``helpers.py:3-4``)."""
    pred = jnp.ravel(pred)
    ref = jnp.ravel(ref)
    return jnp.linalg.norm(ref - pred) / jnp.linalg.norm(ref)


LossFn = Callable[..., jnp.ndarray]
