"""One fused minimax step: collocation points → SA-λ-weighted residual loss
→ parameter cotangents AND the per-point λ gradient-ascent direction, as a
single fusion.

The unfused training step evaluates the fused Taylor residual
(:mod:`.fused`), materialises the ``[N, n_out]`` derivative tables, reduces
them into the λ-weighted MSE, and lets reverse-mode AD transpose the whole
chain.  Two measured costs ride along:

* **HBM round-trips (TPU)** — each layer's channel-stacked activations
  stream through HBM twice (forward store + backward re-read); PERF.md's
  roofline puts the bf16+pallas step at ~16% MFU with HBM traffic as the
  floor.
* **a pathological transpose (CPU/XLA)** — the batched channel matmul
  ``[C, N, w_in] @ W`` reverse-differentiates into a batched double
  contraction that XLA's CPU backend lowers ~4× slower than the
  mathematically identical flat GEMM (measured this round: 170 ms vs 81 ms
  for the same wavefront gradient at N=8192, w=64).

This module removes both by making the *loss term itself* the fused unit:
``sq(layers, w, X) = Σ_p w_p · f_p(X)²`` is a ``jax.custom_vjp`` whose
forward computes the value **and** every cotangent — weight/bias descent
directions, the per-point ``∂/∂w`` that becomes the SA-λ ascent direction,
and ``∂/∂X`` for gradient-based collocation adaptation — in one pass; the
backward is three scalar multiplies.  Because the reduction happens inside
the fusion, the engine owns its data layout: the wavefront runs
``flat_matmul`` (the GEMM-friendly form) whenever the point axis is not
GSPMD-sharded, and the pallas flavor keeps the entire wavefront + its VJP
VMEM-resident per point-tile, so HBM traffic collapses to: points and λ in,
scalar loss and parameter cotangents out.

Every weighting mode of the SA family maps onto the per-point ``w`` channel
(``w = λ²`` for type-1, ``w = g(λ)`` for the g-transform, scalar type-2 λ
multiplies outside) with the λ chain rule composed by ordinary AD *outside*
the fusion — elementwise on ``[N, 1]`` arrays, negligible traffic — so
``ResilientFit``, telemetry, checkpointing, and the optimizer see an
ordinary loss/grad function.

The XLA fallback (``use_pallas=False``) runs the same math as one fused
jaxpr and is the CPU tier-1 path; the pallas kernel is bit-compared against
it in interpret mode (``tests/test_pallas.py``).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .fused import SymbolicUFn, _TableEngine
from .taylor import closure, taylor_derivatives

try:  # pragma: no cover - import guard exercised only off-TPU
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def _sorted_mis(requests: set) -> list:
    return sorted(set(requests) | {()}, key=lambda t: (len(t), t))


def available() -> bool:
    """True when the TPU pallas backend can run (real TPU present)."""
    return _HAS_PLTPU and jax.default_backend() == "tpu"


def n_channels(requests: set) -> int:
    """Channels the wavefront carries for a request set (primal included) —
    the per-layer matmul multiplicity, which is also the analytic FLOP
    multiplier the cost model quotes for the fused kernel
    (:func:`~tensordiffeq_tpu.telemetry.costmodel.analytic_minimax_flops`)."""
    firsts, seconds, thirds, fourths = closure(set(requests))
    return 1 + len(firsts) + len(seconds) + len(thirds) + len(fourths)


def residual_columns(f_model: Callable, varnames: Sequence[str], n_out: int,
                     requests: set) -> int:
    """Column count of the (single-component) residual the loss reduces
    over — 1 for the scalar-output family the minimax fusion serves."""
    ndim = len(varnames)
    X = jnp.zeros((2, ndim), jnp.float32)

    def run(X):
        table = {mi: jnp.zeros((2, n_out), jnp.float32)
                 for mi in _sorted_mis(requests)}
        coords = tuple(X[:, i] for i in range(ndim))
        u = SymbolicUFn(_TableEngine(coords, table), varnames, n_out)
        out = f_model(u, *coords)
        if isinstance(out, tuple):
            raise ValueError("minimax fusion serves single-component "
                             "residuals only")
        return jnp.reshape(out, (2, -1))

    return int(jax.eval_shape(run, X).shape[1])


def build_minimax_sq_fn(f_model: Callable, varnames: Sequence[str],
                        n_out: int, requests: set,
                        layer_shapes: Sequence[tuple],
                        tile: int = 256, precision=None,
                        interpret: bool = False, compute_dtype=None,
                        use_pallas: bool = False,
                        flat_matmul: bool = True) -> Callable:
    """Build ``sq(layers, w, X) -> scalar = Σ_p w_p · f_p(X)²`` as the fused
    minimax unit (see module docstring).

    Args:
      f_model: the user residual (single component; callers gate on
        :func:`residual_columns`).
      requests: canonical multi-indices the residual needs (primal implied).
      layer_shapes: ``[(in, out), ...]`` static layer dims.
      tile: points per grid step of the pallas kernel — the kernel holds
        the tile's wavefront AND its VJP residuals in VMEM, so the budget
        matches :mod:`.pallas_taylor`'s backward tile, not its forward one.
      precision / compute_dtype: forwarded to
        :func:`~.taylor.taylor_derivatives` (bf16 matmul operands with f32
        accumulation under ``compute_dtype=jnp.bfloat16`` — the MXU's
        native single-pass path, end-to-end through value AND cotangents).
      use_pallas: VMEM-resident kernel (TPU, or ``interpret=True`` for CPU
        equivalence tests) vs the fused-XLA jaxpr.
      flat_matmul: run the wavefront in the GEMM-friendly flat layout
        (``[C·N, w]``).  Must be ``False`` when the point axis is
        GSPMD-sharded (``dist=True``) — the reshape would cross the shard.
        The pallas path always runs flat inside the kernel (Mosaic cannot
        lower the batched form's weight-cotangent transpose).

    ``layers`` is the ``[(W, b), ...]`` list; ``w`` is the per-point weight
    column ``[N, 1]`` (λ², g(λ), or ones — see
    :func:`make_minimax_residual_loss`).  The returned callable is
    ``custom_vjp``-wrapped: differentiating through it costs one fused
    forward that already carries every cotangent.
    """
    mis = _sorted_mis(requests)
    ndim = len(varnames)
    n_layers = len(layer_shapes)
    d_in = layer_shapes[0][0]

    def tile_sq(layers, w, x, flat):
        table = taylor_derivatives(list(layers), x, set(mis),
                                   precision=precision, flat_matmul=flat,
                                   compute_dtype=compute_dtype)
        coords = tuple(x[:, i] for i in range(ndim))
        u = SymbolicUFn(_TableEngine(coords, table), varnames, n_out)
        out = f_model(u, *coords)
        f2 = jnp.square(jnp.reshape(out, (x.shape[0], -1)))
        return jnp.sum(w * f2)

    def unflatten(flat):
        return [(flat[2 * i], flat[2 * i + 1]) for i in range(n_layers)]

    if not use_pallas:
        def fused_value(flat_layers, w, X):
            return tile_sq(unflatten(flat_layers), w, X, flat_matmul)

        def fused_value_and_grads(flat_layers, w, X):
            val, vjp = jax.vjp(fused_value, flat_layers, w, X)
            gl, gw, gx = vjp(jnp.ones((), val.dtype))
            return val, (gl, gw, gx)
    else:
        def kernel(*refs):
            x_ref, w_ref = refs[0], refs[1]
            w_refs = refs[2:2 + 2 * n_layers]
            s_ref = refs[2 + 2 * n_layers]
            dwb_refs = refs[3 + 2 * n_layers:3 + 4 * n_layers]
            dw_ref, dx_ref = refs[-2], refs[-1]
            layers = tuple((w_refs[2 * i][...], w_refs[2 * i + 1][...])
                           for i in range(n_layers))

            def f(layers, wt, x):
                return tile_sq(layers, wt, x, True)

            val, vjp = jax.vjp(f, layers, w_ref[...], x_ref[...])
            grads, gw, gx = vjp(jnp.ones((), val.dtype))
            dw_ref[...] = gw
            dx_ref[...] = gx

            i = pl.program_id(0)

            @pl.when(i == 0)
            def _():
                s_ref[...] = val.reshape(1, 1)

            @pl.when(i != 0)
            def _():
                s_ref[...] += val.reshape(1, 1)

            for li, (gW, gb) in enumerate(grads):
                dW_ref, db_ref = dwb_refs[2 * li], dwb_refs[2 * li + 1]

                @pl.when(i == 0)
                def _(dW_ref=dW_ref, db_ref=db_ref, gW=gW, gb=gb):
                    dW_ref[...] = gW
                    db_ref[...] = gb

                @pl.when(i != 0)
                def _(dW_ref=dW_ref, db_ref=db_ref, gW=gW, gb=gb):
                    dW_ref[...] += gW
                    db_ref[...] += gb

        def _whole(shape):  # weight-style block: resident across the grid
            return pl.BlockSpec(shape, lambda i: tuple(0 for _ in shape))

        def _tiled(ncols):  # point-axis block
            return pl.BlockSpec((tile, ncols), lambda i: (i, 0))

        w_specs, wb_shapes = [], []
        for (fan_in, fan_out) in layer_shapes:
            w_specs += [_whole((fan_in, fan_out)), _whole((1, fan_out))]
            wb_shapes += [(fan_in, fan_out), (1, fan_out)]

        def fused_value_and_grads(flat_layers, w, X):
            N = X.shape[0]
            n_tiles = -(-N // tile)
            pad = n_tiles * tile - N
            if pad:
                # pad by REPLICATING a real collocation point, weighted 0:
                # zero weight kills the value/dW contribution, and a valid
                # point keeps the residual finite — an all-zero pad row
                # would evaluate f_model AT the origin, where
                # coordinate-singular PDEs (1/x, log x) produce a NaN that
                # 0·NaN propagates into the whole in-kernel reduction
                X = jnp.concatenate(
                    [X, jnp.broadcast_to(X[:1], (pad, d_in))], 0)
                w = jnp.concatenate([w, jnp.zeros((pad, 1), w.dtype)], 0)
            outs = pl.pallas_call(
                kernel,
                grid=(n_tiles,),
                in_specs=[_tiled(d_in), _tiled(1)] + w_specs,
                out_specs=[_whole((1, 1))] + w_specs
                + [_tiled(1), _tiled(d_in)],
                out_shape=[jax.ShapeDtypeStruct((1, 1), X.dtype)]
                + [jax.ShapeDtypeStruct(s, X.dtype) for s in wb_shapes]
                + [jax.ShapeDtypeStruct((X.shape[0], 1), X.dtype),
                   jax.ShapeDtypeStruct(X.shape, X.dtype)],
                interpret=interpret,
            )(X, w, *flat_layers)
            val = outs[0].reshape(())
            gl = tuple(outs[1:1 + 2 * n_layers])
            gw, gx = outs[-2][:N], outs[-1][:N]
            return val, (gl, gw, gx)

        def fused_value(flat_layers, w, X):
            return fused_value_and_grads(flat_layers, w, X)[0]

    @jax.custom_vjp
    def sq(flat_layers, w, X):
        return fused_value(flat_layers, w, X)

    def sq_fwd(flat_layers, w, X):
        return fused_value_and_grads(flat_layers, w, X)

    def sq_bwd(res, g):
        gl, gw, gx = res
        return (jax.tree_util.tree_map(lambda a: a * g, gl),
                gw * g, gx * g)

    sq.defvjp(sq_fwd, sq_bwd)

    def sq_fn(layers, w, X):
        # bias reshape to [1, fan_out] happens in traced code, so its
        # transpose is handled by the outer AD, not the custom vjp
        flat = tuple(arr if arr.ndim == 2 else arr.reshape(1, -1)
                     for pair in layers for arr in pair)
        return sq(flat, w, X)

    return sq_fn


def make_minimax_residual_loss(sq_fn: Callable,
                               weight_outside_sum: bool = False,
                               g=None) -> Callable:
    """Wrap a :func:`build_minimax_sq_fn` unit as the solver's residual
    loss term ``residual_loss(params, lam_res, X) -> scalar``, reproducing
    :func:`~tensordiffeq_tpu.models.assembly.build_loss_fn`'s λ semantics:

    * no λ            → ``mean(f²)``              (``w = 1``)
    * per-point type-1 → ``mean((λ·f)²)``          (``w = λ²``)
    * ``g`` transform  → ``mean(g(λ)·f²)``         (``w = g(λ)``)
    * scalar type-2    → ``λ · mean(f²)``          (outer multiply)

    The λ chain rule (``∂w/∂λ``) composes by ordinary AD outside the fused
    unit — elementwise on ``[N, 1]`` — so the fused cotangent ``∂loss/∂w``
    becomes the SA-λ gradient-ascent direction with no second traversal.
    """
    from .taylor import extract_mlp_layers

    def residual_loss(params, lam_res, X):
        layers = extract_mlp_layers(params)
        if layers is None:
            raise ValueError(
                "minimax residual loss requires the standard MLP parameter "
                "structure (Dense_0..Dense_k)")
        N = X.shape[0]
        lam = lam_res[0] if len(lam_res) > 0 else None
        outer = None
        if lam is None:
            w = jnp.ones((N, 1), X.dtype)
        elif g is not None:
            w = jnp.broadcast_to(jnp.reshape(g(lam), (-1, 1)), (N, 1))
        elif weight_outside_sum:
            # scalar type-2 / NTK weight: scales the term's mean (per-point
            # λ never reaches this branch — MSE(outside_sum) is scalar-only)
            w = jnp.ones((N, 1), X.dtype)
            outer = jnp.reshape(lam, ())
        else:  # type-1: mean((λ·f)²), per-point or scalar λ
            lam2 = jnp.broadcast_to(jnp.reshape(lam, (-1, 1)), (N, 1))
            w = lam2 * lam2
        loss = sq_fn(layers, w, X) / N
        return loss if outer is None else outer * loss

    return residual_loss
