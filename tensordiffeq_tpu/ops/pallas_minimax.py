"""One fused minimax step: collocation points → SA-λ-weighted residual loss
→ parameter cotangents AND the per-point λ gradient-ascent direction, as a
single fusion — for scalar residuals and E-equation systems alike.

The unfused training step evaluates the fused Taylor residual
(:mod:`.fused`), materialises the ``[N, n_out]`` derivative tables, reduces
them into the λ-weighted MSE, and lets reverse-mode AD transpose the whole
chain.  Two measured costs ride along:

* **HBM round-trips (TPU)** — each layer's channel-stacked activations
  stream through HBM twice (forward store + backward re-read); PERF.md's
  roofline puts the bf16+pallas step at ~16% MFU with HBM traffic as the
  floor.
* **a pathological transpose (CPU/XLA)** — the batched channel matmul
  ``[C, N, w_in] @ W`` reverse-differentiates into a batched double
  contraction that XLA's CPU backend lowers ~4× slower than the
  mathematically identical flat GEMM (measured this round: 170 ms vs 81 ms
  for the same wavefront gradient at N=8192, w=64).

This module removes both by making the *loss term itself* the fused unit:
``sq(layers, w, X) = Σ_e Σ_p w_{p,e} · f_{p,e}(X)²`` is a
``jax.custom_vjp`` whose forward computes the value **and** every cotangent
— weight/bias descent directions, the per-point per-equation ``∂/∂w`` that
becomes the SA-λ ascent direction, and a ``∂/∂X`` summed over equations for
gradient-based collocation adaptation — in one pass; the backward is three
scalar multiplies.  A coupled E-equation system (``f_model`` returning a
tuple — Schrödinger's real/imag pair, reaction–diffusion) stacks its
single-column residual components as E weight channels; E multiplies only
this residual-boundary reduction, never the Taylor wavefront, which all
equations share.  Because the reduction happens inside the fusion, the
engine owns its data layout: the wavefront runs ``flat_matmul`` (the
GEMM-friendly form) whenever the point axis is not GSPMD-sharded, and the
pallas flavor keeps the entire wavefront + its VJP VMEM-resident per
point-tile, so HBM traffic collapses to: points and λ in, scalar loss and
parameter cotangents out.

Every weighting mode of the SA family maps onto the per-point, per-equation
``w`` channels (``w = λ²`` for type-1, ``w = g(λ)`` for the g-transform,
scalar type-2 λ folds linearly into its equation's channel) with the λ
chain rule composed by ordinary AD *outside* the fusion — elementwise on
``[N, E]`` arrays, negligible traffic — so ``ResilientFit``, telemetry,
checkpointing, and the optimizer see an ordinary loss/grad function.

The XLA fallback (``use_pallas=False``) runs the same math as one fused
jaxpr and is the CPU tier-1 path; the pallas kernel is bit-compared against
it in interpret mode (``tests/test_pallas.py``).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .fused import SymbolicUFn, _TableEngine
from .taylor import closure, taylor_derivatives

try:  # pragma: no cover - import guard exercised only off-TPU
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def _sorted_mis(requests: set) -> list:
    return sorted(set(requests) | {()}, key=lambda t: (len(t), t))


def available() -> bool:
    """True when the TPU pallas backend can run (real TPU present)."""
    return _HAS_PLTPU and jax.default_backend() == "tpu"


def n_channels(requests: set) -> int:
    """Channels the wavefront carries for a request set (primal included) —
    the per-layer matmul multiplicity, which is also the analytic FLOP
    multiplier the cost model quotes for the fused kernel
    (:func:`~tensordiffeq_tpu.telemetry.costmodel.analytic_minimax_flops`)."""
    firsts, seconds, thirds, fourths = closure(set(requests))
    return 1 + len(firsts) + len(seconds) + len(thirds) + len(fourths)


def residual_columns(f_model: Callable, varnames: Sequence[str], n_out: int,
                     requests: set) -> int:
    """Number of single-column residual equations ``f_model`` defines —
    the E of the fused reduction ``Σ_e Σ_p w_{p,e}·f_{p,e}²`` and the
    width of its ``w`` channel block.

    A tuple-returning ``f_model`` is an E-equation system (one weight
    channel per component); a plain array is the E=1 scalar family.
    Raises :class:`ValueError` for layouts per-point λ weighting cannot
    serve: any component (or the single residual) that flattens to more
    than one column per point."""
    ndim = len(varnames)
    X = jnp.zeros((2, ndim), jnp.float32)

    def run(X):
        table = {mi: jnp.zeros((2, n_out), jnp.float32)
                 for mi in _sorted_mis(requests)}
        coords = tuple(X[:, i] for i in range(ndim))
        u = SymbolicUFn(_TableEngine(coords, table), varnames, n_out)
        out = f_model(u, *coords)
        parts = out if isinstance(out, tuple) else (out,)
        return [jnp.reshape(p, (2, -1)) for p in parts]

    shapes = jax.eval_shape(run, X)
    for e, s in enumerate(shapes):
        if int(s.shape[1]) != 1:
            raise ValueError(
                f"residual component {e} has {int(s.shape[1])} output "
                "columns; per-point λ weighting is defined for "
                "single-column residual equations")
    return len(shapes)


def build_minimax_sq_fn(f_model: Callable, varnames: Sequence[str],
                        n_out: int, requests: set,
                        layer_shapes: Sequence[tuple],
                        tile: int = 256, precision=None,
                        interpret: bool = False, compute_dtype=None,
                        use_pallas: bool = False,
                        flat_matmul: bool = True) -> Callable:
    """Build ``sq(layers, w, X) -> scalar = Σ_e Σ_p w_{p,e} · f_{p,e}(X)²``
    as the fused minimax unit (see module docstring).

    Args:
      f_model: the user residual — a plain array (E=1) or a tuple of E
        single-column equations (:func:`residual_columns` is the gate and
        the E count).
      requests: canonical multi-indices the residual needs (primal implied).
      layer_shapes: ``[(in, out), ...]`` static layer dims.
      tile: points per grid step of the pallas kernel — the kernel holds
        the tile's wavefront AND its VJP residuals in VMEM, so the budget
        matches :mod:`.pallas_taylor`'s backward tile, not its forward one.
      precision / compute_dtype: forwarded to
        :func:`~.taylor.taylor_derivatives` (bf16 matmul operands with f32
        accumulation under ``compute_dtype=jnp.bfloat16`` — the MXU's
        native single-pass path, end-to-end through value AND cotangents).
      use_pallas: VMEM-resident kernel (TPU, or ``interpret=True`` for CPU
        equivalence tests) vs the fused-XLA jaxpr.
      flat_matmul: run the wavefront in the GEMM-friendly flat layout
        (``[C·N, w]``).  Must be ``False`` when the point axis is
        GSPMD-sharded (``dist=True``) — the reshape would cross the shard.
        The pallas path always runs flat inside the kernel (Mosaic cannot
        lower the batched form's weight-cotangent transpose).

    ``layers`` is the ``[(W, b), ...]`` list; ``w`` is the per-point,
    per-equation weight block ``[N, E]`` (λ², g(λ), ones, or a folded
    type-2 scalar per channel — see :func:`make_minimax_residual_loss`;
    E=1 keeps the historical ``[N, 1]`` column, bit-identical to the
    scalar kernel).  Padding discipline is per channel: pad rows replicate
    a real point at weight 0 in EVERY equation channel.  The returned
    callable is ``custom_vjp``-wrapped: differentiating through it costs
    one fused forward that already carries every cotangent — ``∂/∂w`` is
    ``[N, E]`` (per-equation λ-ascent directions), ``∂/∂X`` is summed over
    equations.  The equation count is exposed as ``sq_fn.n_equations``.
    """
    mis = _sorted_mis(requests)
    ndim = len(varnames)
    n_layers = len(layer_shapes)
    d_in = layer_shapes[0][0]
    # E: validated single-column equations (raises on unservable layouts)
    n_eq = residual_columns(f_model, varnames, n_out, requests)

    def tile_sq(layers, w, x, flat):
        table = taylor_derivatives(list(layers), x, set(mis),
                                   precision=precision, flat_matmul=flat,
                                   compute_dtype=compute_dtype)
        coords = tuple(x[:, i] for i in range(ndim))
        u = SymbolicUFn(_TableEngine(coords, table), varnames, n_out)
        out = f_model(u, *coords)
        parts = out if isinstance(out, tuple) else (out,)
        cols = [jnp.reshape(p, (x.shape[0], -1)) for p in parts]
        stacked = cols[0] if len(cols) == 1 else jnp.concatenate(cols, 1)
        f2 = jnp.square(stacked)
        return jnp.sum(w * f2)

    def unflatten(flat):
        return [(flat[2 * i], flat[2 * i + 1]) for i in range(n_layers)]

    if not use_pallas:
        def fused_value(flat_layers, w, X):
            return tile_sq(unflatten(flat_layers), w, X, flat_matmul)

        def fused_value_and_grads(flat_layers, w, X):
            val, vjp = jax.vjp(fused_value, flat_layers, w, X)
            gl, gw, gx = vjp(jnp.ones((), val.dtype))
            return val, (gl, gw, gx)
    else:
        def kernel(*refs):
            x_ref, w_ref = refs[0], refs[1]
            w_refs = refs[2:2 + 2 * n_layers]
            s_ref = refs[2 + 2 * n_layers]
            dwb_refs = refs[3 + 2 * n_layers:3 + 4 * n_layers]
            dw_ref, dx_ref = refs[-2], refs[-1]
            layers = tuple((w_refs[2 * i][...], w_refs[2 * i + 1][...])
                           for i in range(n_layers))

            def f(layers, wt, x):
                return tile_sq(layers, wt, x, True)

            val, vjp = jax.vjp(f, layers, w_ref[...], x_ref[...])
            grads, gw, gx = vjp(jnp.ones((), val.dtype))
            dw_ref[...] = gw
            dx_ref[...] = gx

            i = pl.program_id(0)

            @pl.when(i == 0)
            def _():
                s_ref[...] = val.reshape(1, 1)

            @pl.when(i != 0)
            def _():
                s_ref[...] += val.reshape(1, 1)

            for li, (gW, gb) in enumerate(grads):
                dW_ref, db_ref = dwb_refs[2 * li], dwb_refs[2 * li + 1]

                @pl.when(i == 0)
                def _(dW_ref=dW_ref, db_ref=db_ref, gW=gW, gb=gb):
                    dW_ref[...] = gW
                    db_ref[...] = gb

                @pl.when(i != 0)
                def _(dW_ref=dW_ref, db_ref=db_ref, gW=gW, gb=gb):
                    dW_ref[...] += gW
                    db_ref[...] += gb

        def _whole(shape):  # weight-style block: resident across the grid
            return pl.BlockSpec(shape, lambda i: tuple(0 for _ in shape))

        def _tiled(ncols):  # point-axis block
            return pl.BlockSpec((tile, ncols), lambda i: (i, 0))

        w_specs, wb_shapes = [], []
        for (fan_in, fan_out) in layer_shapes:
            w_specs += [_whole((fan_in, fan_out)), _whole((1, fan_out))]
            wb_shapes += [(fan_in, fan_out), (1, fan_out)]

        def fused_value_and_grads(flat_layers, w, X):
            N = X.shape[0]
            n_tiles = -(-N // tile)
            pad = n_tiles * tile - N
            if pad:
                # pad by REPLICATING a real collocation point, weighted 0
                # in EVERY equation channel: zero weight kills the
                # value/dW contribution per channel, and a valid point
                # keeps the residual finite — an all-zero pad row would
                # evaluate f_model AT the origin, where coordinate-
                # singular PDEs (1/x, log x) produce a NaN that 0·NaN
                # propagates into the whole in-kernel reduction
                X = jnp.concatenate(
                    [X, jnp.broadcast_to(X[:1], (pad, d_in))], 0)
                w = jnp.concatenate([w, jnp.zeros((pad, n_eq), w.dtype)], 0)
            outs = pl.pallas_call(
                kernel,
                grid=(n_tiles,),
                in_specs=[_tiled(d_in), _tiled(n_eq)] + w_specs,
                out_specs=[_whole((1, 1))] + w_specs
                + [_tiled(n_eq), _tiled(d_in)],
                out_shape=[jax.ShapeDtypeStruct((1, 1), X.dtype)]
                + [jax.ShapeDtypeStruct(s, X.dtype) for s in wb_shapes]
                + [jax.ShapeDtypeStruct((X.shape[0], n_eq), X.dtype),
                   jax.ShapeDtypeStruct(X.shape, X.dtype)],
                interpret=interpret,
            )(X, w, *flat_layers)
            val = outs[0].reshape(())
            gl = tuple(outs[1:1 + 2 * n_layers])
            gw, gx = outs[-2][:N], outs[-1][:N]
            return val, (gl, gw, gx)

        def fused_value(flat_layers, w, X):
            return fused_value_and_grads(flat_layers, w, X)[0]

    @jax.custom_vjp
    def sq(flat_layers, w, X):
        return fused_value(flat_layers, w, X)

    def sq_fwd(flat_layers, w, X):
        return fused_value_and_grads(flat_layers, w, X)

    def sq_bwd(res, g):
        gl, gw, gx = res
        return (jax.tree_util.tree_map(lambda a: a * g, gl),
                gw * g, gx * g)

    sq.defvjp(sq_fwd, sq_bwd)

    def sq_fn(layers, w, X):
        # bias reshape to [1, fan_out] happens in traced code, so its
        # transpose is handled by the outer AD, not the custom vjp
        flat = tuple(arr if arr.ndim == 2 else arr.reshape(1, -1)
                     for pair in layers for arr in pair)
        return sq(flat, w, X)

    # consumers (λ routing, the ascent resampler's ones-weight score pass)
    # size their w block from the unit itself
    sq_fn.n_equations = n_eq
    return sq_fn


def make_minimax_residual_loss(sq_fn: Callable,
                               weight_outside_sum: bool = False,
                               g=None) -> Callable:
    """Wrap a :func:`build_minimax_sq_fn` unit as the solver's residual
    loss term ``residual_loss(params, lam_res, X) -> scalar``, reproducing
    :func:`~tensordiffeq_tpu.models.assembly.build_loss_fn`'s λ semantics
    per equation (``lam_res`` is the solver's per-term λ list — one entry
    per residual equation, ``None`` = non-adaptive):

    * no λ            → ``mean(f²)``              (``w = 1``)
    * per-point type-1 → ``mean((λ·f)²)``          (``w = λ²``)
    * ``g`` transform  → ``mean(g(λ)·f²)``         (``w = g(λ)``)
    * scalar type-2    → ``λ · mean(f²)``          (E=1: outer multiply;
      systems: λ folds linearly into the equation's weight channel, so
      AD's broadcast transpose recovers ``∂loss/∂λ_e = mean(f_e²)``
      exactly)

    For an E-equation system the per-equation columns concatenate into the
    ``[N, E]`` weight block the widened unit reduces over; the total is
    ``Σ_e`` of the generic engine's per-equation terms.  The λ chain rule
    (``∂w/∂λ``) composes by ordinary AD outside the fused unit —
    elementwise on ``[N, E]`` — so the fused cotangent ``∂loss/∂w``
    becomes each equation's SA-λ gradient-ascent direction with no second
    traversal.
    """
    from .taylor import extract_mlp_layers

    n_eq = int(getattr(sq_fn, "n_equations", 1))

    def _weight_column(lam, N, dtype):
        """One equation's ``[N, 1]`` weight column + optional outer scalar
        (the E=1 branch keeps the historical outer multiply; systems fold
        it into the channel)."""
        if lam is None:
            return jnp.ones((N, 1), dtype), None
        if g is not None:
            return (jnp.broadcast_to(jnp.reshape(g(lam), (-1, 1)), (N, 1)),
                    None)
        if weight_outside_sum:
            # scalar type-2 / NTK weight: scales the term's mean (per-point
            # λ never reaches this branch — MSE(outside_sum) is scalar-only)
            return jnp.ones((N, 1), dtype), jnp.reshape(lam, ())
        # type-1: mean((λ·f)²), per-point or scalar λ
        lam2 = jnp.broadcast_to(jnp.reshape(lam, (-1, 1)), (N, 1))
        return lam2 * lam2, None

    def residual_loss(params, lam_res, X):
        layers = extract_mlp_layers(params)
        if layers is None:
            raise ValueError(
                "minimax residual loss requires the standard MLP parameter "
                "structure (Dense_0..Dense_k)")
        N = X.shape[0]
        if n_eq == 1:
            lam = lam_res[0] if len(lam_res) > 0 else None
            w, outer = _weight_column(lam, N, X.dtype)
            loss = sq_fn(layers, w, X) / N
            return loss if outer is None else outer * loss
        cols = []
        for e in range(n_eq):
            lam = lam_res[e] if e < len(lam_res) else None
            w_e, outer_e = _weight_column(lam, N, X.dtype)
            if outer_e is not None:
                # λ_e·mean(f_e²) is linear in λ_e: fold it into the
                # channel so the single fused reduction still covers
                # every equation (the outer multiply cannot separate
                # Σ_e afterwards)
                w_e = w_e * outer_e
            cols.append(w_e)
        return sq_fn(layers, jnp.concatenate(cols, axis=1), X) / N

    return residual_loss
