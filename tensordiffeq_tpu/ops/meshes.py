"""Host-side mesh construction helpers.

These mirror the behavioural contract of the reference's ``multimesh`` /
``flatten_and_stack`` pair (``tensordiffeq/utils.py:72-99``): build an
N-dimensional tensor-product grid from per-axis 1-D arrays and flatten it to a
``[n_points, n_dims]`` design matrix suitable for a pointwise network.

This is problem *assembly*, not the hot path: it runs once on host in NumPy
(float64 for accuracy), and its products are moved to device as constants when
the solver jits the loss.  Keeping it NumPy avoids polluting jit traces with
setup work, exactly the split the XLA compilation model wants.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def multimesh(arrs: Sequence[np.ndarray]) -> list[np.ndarray]:
    """N-D tensor-product grid of 1-D arrays, ``np.meshgrid(indexing='ij')``
    semantics (behaviour parity with reference ``utils.py:72-93``)."""
    return list(np.meshgrid(*[np.asarray(a) for a in arrs], indexing="ij"))


def flatten_and_stack(mesh: Sequence[np.ndarray]) -> np.ndarray:
    """Flatten each grid of ``multimesh`` output and stack columns into an
    ``[n_points, n_dims]`` matrix (reference ``utils.py:96-99``)."""
    return np.stack([np.asarray(m).ravel() for m in mesh], axis=-1)


def grid_points(arrs: Sequence[np.ndarray]) -> np.ndarray:
    """Convenience: ``flatten_and_stack(multimesh(arrs))``."""
    return flatten_and_stack(multimesh(arrs))
