"""Fused residual evaluation: trace ``f_model``'s derivative requests, then
serve them from one stacked Taylor propagation (:mod:`.taylor`).

The user contract is unchanged — ``f_model(u, x, t)`` written with
:func:`~tensordiffeq_tpu.grad` combinators.  At compile time the solver runs
``f_model`` once against a *symbolic* ``u`` whose ``grad`` applications build
multi-indices instead of jvp chains; each call site is checked to receive the
untouched coordinate arguments (object identity), so evaluating ``u`` at
shifted points, transformed coordinates, or unsupported derivative orders
aborts the analysis and the solver silently keeps the generic per-point
autodiff engine.  This static analysis only sees how ``u`` is *used* — it
cannot detect f_models that are legal per-point yet not pointwise when re-run
batched (cross-point reductions like ``jnp.mean(u_x(x, t))``, coordinate
stacking, Python control flow on values), which is why the solver additionally
cross-checks the fused residual numerically against the generic engine on a
small sample before adopting it
(:meth:`~tensordiffeq_tpu.models.collocation.CollocationSolverND._crosscheck_fused`).

When analysis succeeds and the network is the standard tanh MLP, the batched
residual becomes: one :func:`~.taylor.taylor_derivatives` wavefront producing
every requested ∂ᵅu as an ``[N, n_out]`` array, then a vmapped re-run of
``f_model`` where ``u`` and its derivatives are table lookups.  Values agree
with the generic engine to float32 round-off (the contraction order through
the shared stacked matmuls differs from per-point jvp chains, so expect
~1e-4 relative drift, not bit identity) with several times fewer network
traversals.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .derivatives import UFn
from .taylor import canonical, extract_mlp_layers, supported, taylor_derivatives


class _AbortAnalysis(Exception):
    """Internal: f_model used ``u`` in a way the fused engine can't serve."""


class _AnalysisEngine:
    """Records the set of multi-indices ``f_model`` requests."""

    def __init__(self, ndim: int):
        # Distinct boxed scalars: object identity marks "the raw coordinate".
        self.tokens = tuple(np.float32(0.1 + 0.05 * i) for i in range(ndim))
        self.requests: set = set()

    def lookup(self, multi_index, component, coords, n_out):
        if len(coords) != len(self.tokens) or any(
                c is not t for c, t in zip(coords, self.tokens)):
            raise _AbortAnalysis(
                "u was evaluated at transformed or reordered coordinates")
        mi = canonical(multi_index)
        if not supported(mi):
            raise _AbortAnalysis(f"unsupported derivative order {mi}")
        self.requests.add(mi)
        if component is None and n_out > 1:
            return jnp.zeros((n_out,), jnp.float32)
        return jnp.float32(0.0)


class _TableEngine:
    """Serves recorded derivatives from the batched derivative table."""

    def __init__(self, tokens: tuple, table: dict):
        self.tokens = tokens
        self.table = table  # {multi_index: [N, n_out] array}

    def lookup(self, multi_index, component, coords, n_out):
        if len(coords) != len(self.tokens) or any(
                c is not t for c, t in zip(coords, self.tokens)):
            # tdq: allow[bare-raise-discipline] internal invariant guard — unreachable once analyze_f_model accepted the f_model
            raise RuntimeError(
                "fused residual: u evaluated at unexpected coordinates "
                "(analysis should have rejected this f_model)")
        arr = self.table[canonical(multi_index)]
        if component is None and n_out > 1:
            return arr  # [N, n_out]
        return arr[:, 0 if component is None else component]  # [N]


class SymbolicUFn(UFn):
    """A ``UFn`` whose derivative structure is interpreted by an engine
    (analysis recording or table lookup) instead of autodiff."""

    def __init__(self, engine, varnames: Sequence[str], n_out: int = 1,
                 multi_index: tuple = (), component: Optional[int] = None):
        self._engine = engine
        self.varnames = tuple(varnames)
        self._n_out_full = n_out
        self.n_out = 1 if component is not None else n_out
        self._multi_index = multi_index
        self._component = component

    def __call__(self, *coords):
        return self._engine.lookup(self._multi_index, self._component, coords,
                                   self._n_out_full)

    def __getitem__(self, k: int) -> "SymbolicUFn":
        if self.n_out == 1:  # scalar (or already component-selected)
            if k != 0:
                raise IndexError("scalar UFn only has component 0")
            return self
        return SymbolicUFn(self._engine, self.varnames, self._n_out_full,
                           self._multi_index, component=k)

    def differentiate(self, num: int, mode: str) -> "SymbolicUFn":
        return SymbolicUFn(self._engine, self.varnames, self._n_out_full,
                           self._multi_index + (num,),
                           component=self._component)


def mlp_qualifies(net, params):
    """The extracted ``[(W, b), ...]`` layers when the network is the exact
    standard float32 tanh :class:`~tensordiffeq_tpu.networks.MLP` the Taylor
    propagation can differentiate, else ``None``.  Shared gate for the
    forward and discovery solvers — an MLP *subclass* may override
    ``__call__`` while keeping Dense params, and a bf16-configured net would
    diverge from the generic engine's numerics, so both are excluded.
    Returning the layers (not a bool) keeps qualification and extraction a
    single tree walk that cannot disagree."""
    import flax.linen as nn

    from ..networks import MLP
    from .taylor import extract_mlp_layers

    if (type(net) is not MLP
            or net.activation not in (nn.tanh, jnp.tanh)
            or net.dtype != jnp.float32
            or net.param_dtype != jnp.float32):
        return None
    return extract_mlp_layers(params)


class FusedMismatch(ValueError):
    """The fused engine's values disagree with the generic engine's beyond
    the legitimate contraction-order band — the engine is computing
    different math, not merely failing to compile."""

    trace_id = None  # attach_trace hook (tdqlint bare-raise-discipline)


def crosscheck_residuals(generic, fused, rtol: float = 5e-3,
                         atol: float = 1e-5):
    """Compare a fused engine's residual against the generic engine's on the
    same sample points.  Returns ``(ok, reason)``.

    The legitimate contraction-order drift between engines stays ~1e-4
    relative (module docstring); a wrong batched re-interpretation (or a
    wrong-on-hardware pallas kernel) lands far outside the band.  One shared
    default tolerance so the forward and discovery solvers cannot drift
    apart; reduced-precision engines (``compute_dtype``) pass a wider
    band."""
    gen_t = generic if isinstance(generic, tuple) else (generic,)
    fus_t = fused if isinstance(fused, tuple) else (fused,)
    if len(gen_t) != len(fus_t):
        return False, FusedMismatch(
            f"fused residual returned {len(fus_t)} component(s), "
            f"generic returned {len(gen_t)}")
    for i, (g_c, f_c) in enumerate(zip(gen_t, fus_t)):
        g_np, f_np = np.asarray(g_c), np.asarray(f_c)
        if g_np.shape != f_np.shape:
            return False, FusedMismatch(
                f"fused residual component {i} has shape {f_np.shape}, "
                f"generic has {g_np.shape}")
        # scale-relative, not elementwise: engine drift (contraction
        # order, reduced-precision matmuls) is proportional to the
        # residual's overall scale, while the structural bugs this guard
        # exists for (batched re-interpretation, hardware miscompiles)
        # produce O(scale) errors
        err = float(np.max(np.abs(f_np - g_np)))
        scale = float(np.max(np.abs(g_np)))
        # `not (err <= band)`, NOT `err > band`: a NaN-emitting engine
        # makes err NaN, and every comparison with NaN is False — the
        # first form fails it, the second would adopt it
        if not (err <= atol + rtol * scale):
            return False, FusedMismatch(
                f"fused residual disagrees with the generic engine on "
                f"{g_np.shape[0]} sample points (component {i}, max abs "
                f"diff {err:.3e} vs scale {scale:.3e}); the f_model is "
                "likely not pointwise when evaluated batched")
    return True, None


def crosscheck_grads(g_gen, g_fus, rtol: float = 5e-3, atol: float = 1e-5):
    """Leaf-wise gradient agreement between engines — the backward-pass
    counterpart of :func:`crosscheck_residuals`, sharing one tolerance
    policy.  Returns ``(ok, reason)``."""
    gen_leaves = jax.tree_util.tree_leaves(g_gen)
    fus_leaves = jax.tree_util.tree_leaves(g_fus)
    if len(gen_leaves) != len(fus_leaves):
        return False, FusedMismatch(
            f"gradient trees have {len(fus_leaves)} vs {len(gen_leaves)} "
            "leaves")
    for lg, lf in zip(gen_leaves, fus_leaves):
        lg, lf = np.asarray(lg), np.asarray(lf)
        scale = float(np.max(np.abs(lg))) + atol
        err = float(np.max(np.abs(lf - lg)))
        if not (err / scale <= rtol):  # NaN-safe: see crosscheck_residuals
            return False, FusedMismatch(
                f"fused residual GRADIENT disagrees with the generic "
                f"engine (relative error {err / scale:.3e} on a parameter "
                f"leaf); the engine's backward pass is wrong")
    return True, None


def analyze_f_model(f_model: Callable, varnames: Sequence[str],
                    n_out: int, return_reason: bool = False,
                    prefix_args: tuple = ()):
    """Dry-run ``f_model`` symbolically.  Returns the set of canonical
    multi-indices it requests, or ``None`` if it isn't fusable.

    With ``return_reason=True`` returns ``(requests_or_None, reason)`` where
    ``reason`` is the exception that stopped the analysis — an
    :class:`_AbortAnalysis` for structurally-unfusable models, or the user's
    own error (so ``fused=True`` failures can show the real cause instead of
    a generic "cannot be fused").

    ``prefix_args`` are passed between ``u`` and the coordinates — the
    inverse-problem contract ``f_model(u, var, *coords)``
    (:class:`~tensordiffeq_tpu.models.discovery.DiscoveryModel`)."""
    engine = _AnalysisEngine(len(varnames))
    u = SymbolicUFn(engine, varnames, n_out)
    reason = None
    try:
        f_model(u, *prefix_args, *engine.tokens)
    except _AbortAnalysis as e:
        reason = e
    except Exception as e:
        # anything else (typos in f_model, shape errors on the dummies, …):
        # fall back so the generic engine surfaces the real error in context
        reason = e
    requests = None if reason is not None else engine.requests | {()}
    return (requests, reason) if return_reason else requests


def make_fused_residual(f_model: Callable, varnames: Sequence[str],
                        n_out: int, requests: set,
                        precision=None,
                        table_producer: Optional[Callable] = None,
                        has_prefix_arg: bool = False,
                        return_primal: bool = False,
                        compute_dtype=None) -> Callable:
    """Build ``residual(params, X) -> [N] | tuple of [N]`` backed by one
    Taylor propagation.  ``params`` must be an
    :func:`~.taylor.extract_mlp_layers`-compatible MLP tree.

    ``table_producer(layers, X) -> {mi: [N, n_out]}`` overrides the XLA
    propagation — e.g. the VMEM-resident pallas kernel
    (:func:`~.pallas_taylor.build_pallas_table_fn`).

    ``has_prefix_arg=True`` builds ``residual(params, X, var)`` for the
    inverse-problem contract ``f_model(u, var, *coords)`` — ``var`` is a
    traced pytree (the trainable PDE coefficients), multiplying the table
    lookups like any other batched value.

    ``return_primal=True`` returns ``(residual, u)`` with ``u = table[()]``
    — the propagation always computes the primal, so a caller whose data
    loss evaluates at the SAME ``X`` (the discovery solver) saves one full
    network forward per step by taking it from here instead of ``apply_fn``."""
    ndim = len(varnames)

    def residual(params, X, *prefix):
        layers = extract_mlp_layers(params)
        if layers is None:
            raise ValueError(
                "fused residual requires the standard MLP parameter "
                "structure (Dense_0..Dense_k)")
        if table_producer is not None:
            table = table_producer(layers, X)
        else:
            table = taylor_derivatives(layers, X, requests,
                                       precision=precision,
                                       compute_dtype=compute_dtype)

        # ONE batched re-run of f_model: lookups return whole [N] columns
        # (scalar arithmetic in f_model broadcasts over the batch exactly as
        # it would over vmap tracers), so no per-point vmap layer is needed.
        coords = tuple(X[:, i] for i in range(ndim))
        u = SymbolicUFn(_TableEngine(coords, table), varnames, n_out)
        out = f_model(u, *prefix, *coords)
        if return_primal:
            return out, table[()]
        return out

    if not has_prefix_arg:
        def residual_no_prefix(params, X):
            return residual(params, X)
        return residual_no_prefix
    return residual
