"""N-dimensional box domains for collocation PINNs.

Capability parity with the reference ``tensordiffeq/domains.py:5-31``
(``DomainND.add`` / ``generate_collocation_points``), with a cleaner accessor
API on top.  The legacy ``domaindict`` structure (keys like ``"xlinspace"``,
``"xupper"``) is kept so reference example scripts translate line-for-line
(e.g. ``Domain.domaindict[0]['xlinspace']``, ``examples/AC-SA.py:74``).

Collocation sampling is deterministic under an explicit ``seed`` — JAX-style
explicit randomness instead of the reference's global-RNG draws.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .sampling import LatinHypercubeSample


class DomainND:
    """A box domain over named variables, one optionally marked as time.

    Example::

        domain = DomainND(["x", "t"], time_var="t")
        domain.add("x", [-1.0, 1.0], fidel=512)
        domain.add("t", [0.0, 1.0], fidel=201)
        domain.generate_collocation_points(50_000, seed=0)
    """

    def __init__(self, var: Sequence[str], time_var: Optional[str] = None):
        self.vars = list(var)
        self.time_var = time_var
        self.domaindict: list[dict] = []
        self.domain_ids: list[str] = []
        self.X_f: Optional[np.ndarray] = None

    def add(self, token: str, vals: Sequence[float], fidel: int):
        """Register variable ``token`` with range ``vals=[lo, hi]`` and mesh
        fidelity ``fidel`` (number of linspace points used for BC/IC faces)."""
        if token not in self.vars:
            raise ValueError(f"Variable {token!r} was not declared in {self.vars}")
        self.domain_ids.append(token)
        self.domaindict.append({
            "identifier": token,
            "range": list(vals),
            token + "fidelity": fidel,
            token + "linspace": np.linspace(vals[0], vals[1], fidel),
            token + "upper": vals[1],
            token + "lower": vals[0],
        })

    # -- clean accessors ----------------------------------------------------
    def var_dict(self, var: str) -> dict:
        return next(d for d in self.domaindict if d["identifier"] == var)

    def linspace(self, var: str) -> np.ndarray:
        return self.var_dict(var)[var + "linspace"]

    def fidelity(self, var: str) -> int:
        return self.var_dict(var)[var + "fidelity"]

    def bounds(self, var: str) -> tuple[float, float]:
        lo, hi = self.var_dict(var)["range"]
        return float(lo), float(hi)

    @property
    def xlimits(self) -> np.ndarray:
        """``[nx, 2]`` bounds array in declaration order of ``self.vars``."""
        return np.array([self.bounds(v) for v in self.vars], dtype=np.float64)

    @property
    def ndim(self) -> int:
        return len(self.vars)

    def var_index(self, var: str) -> int:
        return self.vars.index(var)

    # -- collocation sampling ----------------------------------------------
    def generate_collocation_points(self, N_f: int, seed: Optional[int] = None,
                                    criterion: str = "c") -> np.ndarray:
        """Latin-Hypercube sample ``N_f`` interior points over the box
        (reference ``domains.py:12-20``).  Stores and returns ``X_f`` with
        shape ``[N_f, ndim]`` in ``self.vars`` column order."""
        missing = [v for v in self.vars if v not in self.domain_ids]
        if missing:
            raise ValueError(f"Domain variables not yet added: {missing}")
        self.X_f = LatinHypercubeSample(N_f, self.xlimits, criterion=criterion,
                                        seed=seed)
        return self.X_f
