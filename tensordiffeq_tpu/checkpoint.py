"""Full training-state checkpoints: params + SA λ + optimizer moments.

The reference can only persist the Keras network (``models.py:315-319``) —
its λ weights and Adam/L-BFGS state are silently lost on reload (SURVEY §5),
so "resume" actually restarts the minimax from scratch.  Here the complete
trainable state round-trips:

* primary path: `orbax.checkpoint` ``StandardCheckpointer`` (async-capable,
  sharding-aware — the right tool once states are sharded over a mesh);
* fallback: `flax.serialization` msgpack bytes in a single file (used when
  orbax is unavailable or the state contains objects orbax rejects).

Both are behind the same two functions, keyed by a directory path::

    save_checkpoint(path, state)
    state = restore_checkpoint(path, template)   # template supplies structure

``template`` must be a pytree with the same structure/shapes as the saved
state (build it from a freshly compiled solver, as
``CollocationSolverND.restore_checkpoint`` does).

Crash-safety protocol (what a preemptible environment actually needs):

* every save lands in a ``<path>.tmp`` sibling first, every payload file
  is **fsynced**, and the directory swaps in via atomic renames — a
  process killed at ANY point leaves a restorable checkpoint on disk;
* the previous checkpoint is **retained** at ``<path>.old`` (keep-last
  K=2), not discarded: rollback and corruption fallback both need a
  second intact generation;
* the meta file embeds a **content checksum** over every payload byte;
  :func:`restore_checkpoint` validates it (and the pytree shapes) before
  trusting a generation, and falls back to ``<path>.old`` when the newest
  is torn/corrupt — raising :class:`CheckpointCorrupted` only when no
  generation survives.  ``tests/test_resilience.py`` drives this with
  chaos-torn writes.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Optional

import jax
import numpy as np

from .telemetry import log_event

_META = "tdq_meta.json"
_FLAX_FILE = "state.msgpack"
_SHARD_DIR = "shards"
_CLUSTER_FILE = "cluster.json"

#: How long multi-process saves wait on their peers' shard files before
#: proceeding without them (a dead host must not wedge the survivors'
#: flush; the incomplete generation fails shard-coverage validation at
#: restore and the previous complete one is used instead).
SYNC_TIMEOUT_S = float(os.environ.get("TDQ_CKPT_SYNC_TIMEOUT_S", "120"))

# per-process save sequence number: every process of a job calls
# save_checkpoint in lockstep (same training-loop cadence), so the
# counter doubles as the file-based barrier's round id
_save_seq = 0


class TemplateMismatch(ValueError):
    """The caller's template does not match what this checkpoint holds —
    a CONFIG error (wrong layer sizes, different λ setup), not corruption.
    Never absorbed by the previous-generation fallback: silently resuming
    an older run would be worse than the error."""

    trace_id = None  # attach_trace hook (tdqlint bare-raise-discipline)


class CheckpointCorrupted(RuntimeError):
    """No checkpoint generation under this path survived validation.
    ``failures`` maps each candidate directory to why it was rejected."""

    trace_id = None

    def __init__(self, path: str, failures: dict):
        self.path = path
        self.failures = dict(failures)
        detail = "; ".join(f"{d}: {why}" for d, why in failures.items())
        super().__init__(
            f"every checkpoint generation under {path} failed validation "
            f"({detail})")


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(np.asarray, tree)


# --------------------------------------------------------------------- #
# Topology-portable sharded state (multi-host / elastic restore)
#
# A leaf that spans processes cannot be pulled to any single host
# (``np.asarray`` on a non-fully-addressable array is illegal), so each
# process persists ONLY its addressable shards, and the meta records the
# global logical shape per leaf — the manifest.  Restore reassembles the
# global host array from whatever shard files the generation holds and
# re-shards onto the CURRENT mesh, which is how an 8-device checkpoint
# resumes on a 4-device slice (and vice versa): the re-shard happens at
# restore, against host arrays, never in-flight against live device state.
# --------------------------------------------------------------------- #

def _is_shard_leaf(leaf, force: bool) -> bool:
    """Should this leaf ride the per-shard store?  Always when no single
    process can address all of it; under ``force`` (tests, explicit
    topology-portable saves) also when it is genuinely split over >1
    device (a replicated leaf gathers fine and stays in the state file)."""
    if not isinstance(leaf, jax.Array):
        return False
    if not leaf.is_fully_addressable:
        return True
    if not force or leaf.ndim == 0:
        return False
    segs = {tuple((sl.start, sl.stop, sl.step) for sl in s.index)
            for s in leaf.addressable_shards}  # slices aren't hashable <3.12
    return len(segs) > 1


def _segment_bounds(index, shape) -> list:
    """Normalise a shard's index (tuple of slices) to explicit
    ``[[start, stop], ...]`` per dimension."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _write_shards(tmp: str, sharded: dict, save_id: int) -> None:
    """Persist this process's addressable shards of every sharded leaf
    (one ``.npz`` + one index JSON per process; the index is written last
    via atomic rename — it is the "this process is done" marker the
    coordinator waits on)."""
    proc = jax.process_index()
    sdir = os.path.join(tmp, _SHARD_DIR)
    os.makedirs(sdir, exist_ok=True)
    arrays, leaves_meta = {}, {}
    for i, leaf in sharded.items():
        segs = []
        for s in leaf.addressable_shards:
            if s.replica_id != 0:
                continue  # one writer per distinct global segment
            key = f"l{i}_s{len(segs)}"
            arrays[key] = np.asarray(s.data)
            segs.append({"key": key,
                         "bounds": _segment_bounds(s.index, leaf.shape)})
        leaves_meta[str(i)] = {
            "global_shape": [int(d) for d in leaf.shape],
            "dtype": np.dtype(leaf.dtype).name,
            "segments": segs,
        }
    npz_rel = os.path.join(_SHARD_DIR, f"proc{proc}.npz")
    with open(os.path.join(tmp, npz_rel), "wb") as fh:
        np.savez(fh, **arrays)
    idx = {"proc": proc, "save_id": int(save_id), "file": npz_rel,
           "leaves": leaves_meta}
    idx_path = os.path.join(sdir, f"proc{proc}.json")
    with open(idx_path + ".part", "w") as fh:
        json.dump(idx, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(idx_path + ".part", idx_path)


def _wait_for(predicate, what: str, timeout_s: float = None) -> bool:
    """Poll ``predicate`` until true or timeout; the file-based barrier
    primitive multi-process saves coordinate through (no collective, no
    jax internals — a dead peer costs a bounded wait, never a hang)."""
    timeout_s = SYNC_TIMEOUT_S if timeout_s is None else timeout_s
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    log_event("checkpoint", f"timed out after {timeout_s:.0f}s waiting for "
              f"{what}; continuing without it", level="warning",
              verbose=False, what=what, timeout_s=timeout_s)
    return False


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _assemble_sharded(path: str, manifest: dict, state):
    """Rebuild every manifest leaf as a full host array from the shard
    files under ``path`` and graft them into ``state`` (whose manifest
    leaves are placeholders).  Raises ``ValueError`` on incomplete
    coverage (e.g. a flush that lost a host's shards) — the caller's
    generation-fallback then applies."""
    sdir = os.path.join(path, _SHARD_DIR)
    want = {int(i): m for i, m in manifest["leaves"].items()}
    bufs = {i: np.zeros(m["global_shape"], np.dtype(m["dtype"]))
            for i, m in want.items()}
    filled = {i: 0 for i in want}
    indexes = sorted(f for f in os.listdir(sdir)
                     if f.startswith("proc") and f.endswith(".json")) \
        if os.path.isdir(sdir) else []
    for rel in indexes:
        idx = _read_json(os.path.join(sdir, rel))
        if idx is None:
            raise ValueError(f"unreadable shard index {rel}")
        with np.load(os.path.join(path, idx["file"])) as npz:
            for si, m in idx["leaves"].items():
                i = int(si)
                if i not in want:
                    continue
                for seg in m["segments"]:
                    sl = tuple(slice(a, b) for a, b in seg["bounds"])
                    data = npz[seg["key"]]
                    bufs[i][sl] = data
                    filled[i] += int(data.size)
    for i, m in want.items():
        total = int(np.prod(m["global_shape"])) if m["global_shape"] else 1
        if filled[i] < total:
            raise ValueError(
                f"shard coverage incomplete for leaf {i} "
                f"({filled[i]}/{total} elements; a host's shards are "
                "missing — likely a flush after host loss)")
    leaves, treedef = jax.tree_util.tree_flatten(state)
    for i, buf in bufs.items():
        leaves[i] = buf
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _payload_files(path: str) -> list:
    """Every file under ``path`` except the meta, as sorted relative paths
    — the checksum domain (sorted so the digest is walk-order independent)."""
    out = []
    for root, _, files in os.walk(path):
        for f in files:
            if f == _META:
                continue
            out.append(os.path.relpath(os.path.join(root, f), path))
    return sorted(out)


def _digest_dir(path: str) -> dict:
    """Content checksum over every payload byte (file names included, so a
    missing or renamed file also fails validation)."""
    h = hashlib.sha256()
    files = _payload_files(path)
    for rel in files:
        h.update(rel.encode("utf-8"))
        h.update(b"\x00")
        with open(os.path.join(path, rel), "rb") as fh:
            for block in iter(lambda: fh.read(1 << 20), b""):
                h.update(block)
    return {"algo": "sha256", "digest": h.hexdigest(), "n_files": len(files)}


def _fsync_dir_tree(path: str) -> None:
    """fsync every file under ``path`` plus the directories themselves —
    the rename-based swap below is only crash-atomic if the payload bytes
    reached disk first.  Best-effort on filesystems without dir fsync."""
    for root, dirs, files in os.walk(path):
        for f in files:
            with open(os.path.join(root, f), "rb") as fh:
                os.fsync(fh.fileno())
        try:
            fd = os.open(root, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass


def checkpoint_exists(path: str) -> bool:
    """Is there any restorable generation (current, parked, or previous)
    under ``path``?"""
    path = os.path.abspath(path)
    return any(os.path.exists(os.path.join(c, _META))
               for c in (path, path + ".old"))


def save_checkpoint(path: str, state: dict, meta: dict | None = None,
                    extra_files: dict | None = None,
                    sharded: Optional[bool] = None) -> None:
    """Write ``state`` (a pytree dict) under directory ``path``.

    ``meta`` is an optional JSON-serialisable dict stored alongside (losses
    history, iteration counters, …).

    ``extra_files`` maps checkpoint-relative paths to raw ``bytes`` written
    alongside the state (e.g. the fleet layer's serialized AOT programs,
    ``aot/u_256.bin``).  They land in the same ``.tmp`` staging directory
    BEFORE the content checksum is computed, so they ride the full
    crash-safety protocol: fsynced, checksummed, atomically swapped, and
    validated by :func:`restore_checkpoint` exactly like the state payload
    — a torn AOT blob fails the whole generation instead of silently
    serving a corrupt program.

    The write is crash-safe: everything lands in a ``<path>.tmp`` sibling
    first (payloads fsynced, content checksum embedded in the meta), then
    swaps in via directory renames.  A process killed at ANY point leaves
    a restorable checkpoint on disk — either the new one, or the previous
    one (parked at ``<path>.old``, which :func:`restore_checkpoint` falls
    back to).  The parked generation is KEPT (last K=2): it is both the
    mid-swap-kill safety net and the fallback when the newest generation
    is later found corrupt.  This matters because the mid-run checkpoint
    hook (``fit(checkpoint_dir=)``) exists precisely for environments that
    kill processes at arbitrary moments; an overwrite-in-place would put
    the only resume point in the blast radius of every periodic save.

    ``sharded``: topology-portable per-shard layout.  ``None`` (default)
    auto-enables it when the job is multi-process or any leaf spans
    devices no single process addresses; ``True`` forces it for every
    leaf genuinely split over >1 device (how single-process tests
    exercise the elastic-restore format); ``False`` forces the plain
    host-gather layout (errors on non-addressable leaves).  In sharded
    mode each process writes only its own shards; rank 0 owns the state
    file, meta (with the global-shape manifest) and the atomic promote,
    coordinating through bounded file waits — a dead peer costs
    :data:`SYNC_TIMEOUT_S`, never a hang, and the resulting incomplete
    generation fails shard-coverage validation at restore (falling back
    to the previous complete one) instead of resurrecting partial state.
    """
    import shutil

    global _save_seq
    path = os.path.abspath(path)
    tmp, old = path + ".tmp", path + ".old"
    nproc = jax.process_count()
    leaves, treedef = jax.tree_util.tree_flatten(state)
    if sharded is None:
        sharded = nproc > 1 or any(
            isinstance(l, jax.Array) and not l.is_fully_addressable
            for l in leaves)
    # global logical shapes — recorded BEFORE any shard substitution so
    # restores validate the caller's template against what the state
    # means, not how this topology happened to store it
    leaf_shapes = [list(np.shape(l)) for l in leaves]
    save_id, _save_seq = _save_seq, _save_seq + 1
    shard_manifest = None
    if sharded:
        sharded_leaves = {i: l for i, l in enumerate(leaves)
                          if _is_shard_leaf(l, force=True)}
        if jax.process_index() != 0:
            # follower: wait for rank 0 to open this round's staging dir,
            # contribute shards, then wait for the promote (or the next
            # round opening — rank 0 moved on without us)
            ok = _wait_for(
                lambda: (_read_json(os.path.join(tmp, _CLUSTER_FILE))
                         or {}).get("save_id") == save_id,
                f"save round {save_id} staging dir")
            if ok:
                _write_shards(tmp, sharded_leaves, save_id)
                _wait_for(
                    lambda: (_read_json(os.path.join(tmp, _CLUSTER_FILE))
                             or {}).get("save_id") != save_id,
                    f"save round {save_id} promote")
            return
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        with open(os.path.join(tmp, _CLUSTER_FILE), "w") as fh:
            json.dump({"nproc": nproc, "save_id": save_id}, fh)
        _write_shards(tmp, sharded_leaves, save_id)
        if nproc > 1:
            sdir = os.path.join(tmp, _SHARD_DIR)
            _wait_for(
                lambda: all(os.path.exists(
                    os.path.join(sdir, f"proc{p}.json"))
                    for p in range(nproc)),
                f"all {nproc} processes' shard files")
        shard_manifest = {
            "nproc": nproc,
            "leaves": {str(i): {"global_shape": [int(d) for d in l.shape],
                                "dtype": np.dtype(l.dtype).name}
                       for i, l in sharded_leaves.items()}}
        # the state file carries zero-size placeholders where the manifest
        # leaves live; restore grafts the assembled global arrays back in
        state = jax.tree_util.tree_unflatten(treedef, [
            np.zeros((0,), np.dtype(l.dtype)) if i in sharded_leaves
            else np.asarray(l) for i, l in enumerate(leaves)])
    else:
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        state = _to_host(state)
    backend = "flax"
    if shard_manifest is None:
        try:
            import orbax.checkpoint as ocp
            ckptr = ocp.StandardCheckpointer()
            ckptr.save(os.path.join(tmp, "state"), state)
            ckptr.wait_until_finished()
            backend = "orbax"
        except Exception:
            import flax.serialization
            with open(os.path.join(tmp, _FLAX_FILE), "wb") as fh:
                fh.write(flax.serialization.to_bytes(state))
    else:
        # sharded generations always use the flax backend: orbax's own
        # multi-process machinery would fight the file-based protocol
        import flax.serialization
        with open(os.path.join(tmp, _FLAX_FILE), "wb") as fh:
            fh.write(flax.serialization.to_bytes(state))
    for rel, blob in (extra_files or {}).items():
        rel = os.path.normpath(rel)
        if os.path.isabs(rel) or rel.startswith(".."):
            raise ValueError(f"extra file path {rel!r} escapes the "
                             "checkpoint directory")
        if os.path.basename(rel) == _META:
            raise ValueError(f"extra file {rel!r} would shadow the "
                             "checkpoint meta")
        dest = os.path.join(tmp, rel)
        os.makedirs(os.path.dirname(dest) or tmp, exist_ok=True)
        with open(dest, "wb") as fh:
            fh.write(bytes(blob))
    with open(os.path.join(tmp, _META), "w") as fh:
        json.dump({"backend": backend, "meta": meta or {},
                   # restores compare these against the caller's template
                   # BEFORE any backend load, so a wrong-config restore is
                   # diagnosed as TemplateMismatch (and never triggers the
                   # corruption fallback) regardless of which backend error
                   # a mismatched deserialisation would otherwise raise.
                   # Sharded saves record the GLOBAL logical shapes — the
                   # topology-portable contract a different device count
                   # restores against.
                   "leaf_shapes": leaf_shapes,
                   "save_id": save_id,
                   **({"sharded": shard_manifest}
                      if shard_manifest is not None else {}),
                   "checksum": _digest_dir(tmp)}, fh)
        fh.flush()
        os.fsync(fh.fileno())
    _fsync_dir_tree(tmp)
    # swap: park the previous checkpoint, promote the new one, KEEP the
    # parked copy (K=2).  Both renames are atomic on POSIX.  Only clear a
    # stale ``.old`` when there is a current ``path`` to park in its place:
    # if a prior save died mid-swap, ``.old`` holds the ONLY restorable
    # checkpoint until the rename below promotes ``tmp``.
    if os.path.exists(path):
        shutil.rmtree(old, ignore_errors=True)
        os.rename(path, old)
    os.rename(tmp, path)
    from .resilience.chaos import active_chaos
    c = active_chaos()
    if c is not None:
        c.on_checkpoint_saved(path)


def resolve_checkpoint_dir(path: str) -> str:
    """The directory a restore should actually read: ``path`` itself, or
    the parked ``<path>.old`` when a killed save left only that (callers
    that peek at ``tdq_meta.json`` themselves must use this too)."""
    path = os.path.abspath(path)
    if not os.path.exists(os.path.join(path, _META)) \
            and os.path.exists(os.path.join(path + ".old", _META)):
        return path + ".old"
    return path


def verify_checkpoint(path: str) -> None:
    """Validate one checkpoint directory's content checksum against its
    meta.  Raises ``ValueError`` on mismatch (and ``OSError`` when files
    are missing); checkpoints from before the checksum era pass (nothing
    recorded to validate against)."""
    with open(os.path.join(path, _META)) as fh:
        info = json.load(fh)
    want = info.get("checksum")
    if want is None:
        return
    got = _digest_dir(path)
    if got["digest"] != want.get("digest"):
        raise ValueError(
            f"content checksum mismatch ({got['n_files']} files, "
            f"{got['digest'][:12]}… != recorded {str(want.get('digest'))[:12]}…)"
            " — torn or corrupted write")


def _template_shape_check(saved_shapes, template) -> None:
    # np.shape reads the GLOBAL logical shape off a jax Array without
    # materialising it — required for multi-host templates, whose leaves
    # may span devices this process cannot address
    t_shapes = [tuple(np.shape(leaf))
                for leaf in jax.tree_util.tree_leaves(template)]
    saved = [tuple(s) for s in saved_shapes]
    if len(saved) != len(t_shapes):
        raise TemplateMismatch(
            f"checkpoint has {len(saved)} array leaves but the template "
            f"has {len(t_shapes)}; was this checkpoint saved for a "
            "different configuration?")
    for t, s in zip(t_shapes, saved):
        if t != s:
            raise TemplateMismatch(
                f"checkpoint leaf shape {s} does not match the template's "
                f"{t}; was this checkpoint saved for a different "
                "configuration?")


def _restore_one(path: str, template: dict) -> tuple[dict, dict]:
    """Load + validate a single checkpoint directory (no fallback)."""
    verify_checkpoint(path)
    with open(os.path.join(path, _META)) as fh:
        info = json.load(fh)
    if "leaf_shapes" in info:
        # config-vs-corruption triage BEFORE the backend load: a template
        # that cannot match raises TemplateMismatch here, so a backend
        # deserialisation error below really does mean a damaged payload
        _template_shape_check(info["leaf_shapes"], template)
    manifest = info.get("sharded")
    if manifest is not None:
        # topology-portable generation: the state file holds placeholders
        # for the manifest leaves; load it against a placeholder template,
        # then reassemble each global array from the per-process shard
        # files (coverage-validated) — the caller re-shards onto ITS mesh
        import flax.serialization
        want = set(manifest["leaves"])
        leaves, treedef = jax.tree_util.tree_flatten(template)
        placeheld = jax.tree_util.tree_unflatten(treedef, [
            np.zeros((0,), np.dtype(manifest["leaves"][str(i)]["dtype"]))
            if str(i) in want else leaf for i, leaf in enumerate(leaves)])
        with open(os.path.join(path, _FLAX_FILE), "rb") as fh:
            state = flax.serialization.from_bytes(placeheld, fh.read())
        state = _assemble_sharded(path, manifest, state)
    elif info["backend"] == "orbax":
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        state = ckptr.restore(os.path.join(os.path.abspath(path), "state"),
                              _to_host(template))
    else:
        import flax.serialization
        with open(os.path.join(path, _FLAX_FILE), "rb") as fh:
            state = flax.serialization.from_bytes(template, fh.read())
    # orbax will happily hand back whatever shapes were saved — validate
    # against the template so a wrong-config restore fails loudly here
    # (covers pre-leaf_shapes-era checkpoints; newer ones were already
    # triaged above)
    _template_shape_check([np.shape(s) for s in
                           jax.tree_util.tree_leaves(state)], template)
    return state, info["meta"]


def restore_checkpoint(path: str, template: dict) -> tuple[dict, dict]:
    """Load the state saved under ``path``.  ``template`` provides the pytree
    structure (and, for the orbax path, shape/dtype guidance).  Returns
    ``(state, meta)``.

    Restore order: the current generation, then the parked previous one
    (``<path>.old`` — present after a killed mid-swap save AND, since the
    keep-last-2 protocol, after every completed save).  A generation whose
    content checksum fails, whose files are missing, or whose payload
    cannot be deserialised is skipped with a logged warning instead of
    crashing the restore; only when NO generation survives does
    :class:`CheckpointCorrupted` raise.

    Template-shape mismatches are NOT absorbed by the fallback: the newest
    generation deserialising cleanly into the wrong shapes means the
    caller compiled a different configuration — that error propagates
    (falling back would silently resume from an older run).
    """
    path = os.path.abspath(path)
    candidates = [c for c in (path, path + ".old")
                  if os.path.exists(os.path.join(c, _META))]
    if not candidates:
        raise FileNotFoundError(
            f"no checkpoint meta under {path} (or its .old sibling)")
    failures: dict = {}
    for i, cand in enumerate(candidates):
        try:
            state, meta = _restore_one(cand, template)
        except TemplateMismatch:
            raise  # config mismatch, not corruption — never fall back
        except Exception as e:
            failures[cand] = f"{type(e).__name__}: {e}"
        else:
            if i > 0 or failures:
                log_event("checkpoint",
                          f"restored the previous generation {cand} "
                          f"(newer one rejected: "
                          f"{'; '.join(failures.values()) or 'missing'})",
                          level="warning", verbose=True, restored=cand,
                          failures=failures)
            return state, meta
    raise CheckpointCorrupted(path, failures)
