"""Full training-state checkpoints: params + SA λ + optimizer moments.

The reference can only persist the Keras network (``models.py:315-319``) —
its λ weights and Adam/L-BFGS state are silently lost on reload (SURVEY §5),
so "resume" actually restarts the minimax from scratch.  Here the complete
trainable state round-trips:

* primary path: `orbax.checkpoint` ``StandardCheckpointer`` (async-capable,
  sharding-aware — the right tool once states are sharded over a mesh);
* fallback: `flax.serialization` msgpack bytes in a single file (used when
  orbax is unavailable or the state contains objects orbax rejects).

Both are behind the same two functions, keyed by a directory path::

    save_checkpoint(path, state)
    state = restore_checkpoint(path, template)   # template supplies structure

``template`` must be a pytree with the same structure/shapes as the saved
state (build it from a freshly compiled solver, as
``CollocationSolverND.restore_checkpoint`` does).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np

_META = "tdq_meta.json"
_FLAX_FILE = "state.msgpack"


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(np.asarray, tree)


def save_checkpoint(path: str, state: dict, meta: dict | None = None) -> None:
    """Write ``state`` (a pytree dict) under directory ``path``.

    ``meta`` is an optional JSON-serialisable dict stored alongside (losses
    history, iteration counters, …).

    The write is crash-safe: everything lands in a ``<path>.tmp`` sibling
    first, then swaps in via directory renames.  A process killed at ANY
    point leaves a restorable checkpoint on disk — either the new one, or
    the previous one (possibly parked at ``<path>.old``, which
    :func:`restore_checkpoint` falls back to).  This matters because the
    mid-run checkpoint hook (``fit(checkpoint_dir=)``) exists precisely
    for environments that kill processes at arbitrary moments; an
    overwrite-in-place would put the only resume point in the blast
    radius of every periodic save.
    """
    import shutil

    path = os.path.abspath(path)
    tmp, old = path + ".tmp", path + ".old"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    state = _to_host(state)
    backend = "flax"
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(tmp, "state"), state)
        ckptr.wait_until_finished()
        backend = "orbax"
    except Exception:
        import flax.serialization
        with open(os.path.join(tmp, _FLAX_FILE), "wb") as fh:
            fh.write(flax.serialization.to_bytes(state))
    with open(os.path.join(tmp, _META), "w") as fh:
        json.dump({"backend": backend, "meta": meta or {}}, fh)
    # swap: park the previous checkpoint, promote the new one, then drop
    # the parked copy.  Both renames are atomic on POSIX.  Only clear a
    # stale ``.old`` when there is a current ``path`` to park in its place:
    # if a prior save died mid-swap, ``.old`` holds the ONLY restorable
    # checkpoint until the rename below promotes ``tmp``.
    if os.path.exists(path):
        shutil.rmtree(old, ignore_errors=True)
        os.rename(path, old)
    os.rename(tmp, path)
    shutil.rmtree(old, ignore_errors=True)


def resolve_checkpoint_dir(path: str) -> str:
    """The directory a restore should actually read: ``path`` itself, or
    the parked ``<path>.old`` when a killed save left only that (callers
    that peek at ``tdq_meta.json`` themselves must use this too)."""
    path = os.path.abspath(path)
    if not os.path.exists(os.path.join(path, _META)) \
            and os.path.exists(os.path.join(path + ".old", _META)):
        return path + ".old"
    return path


def restore_checkpoint(path: str, template: dict) -> tuple[dict, dict]:
    """Load the state saved under ``path``.  ``template`` provides the pytree
    structure (and, for the orbax path, shape/dtype guidance).  Returns
    ``(state, meta)``.

    If ``path`` is missing but a ``<path>.old`` sibling exists (a save was
    killed mid-swap), the parked previous checkpoint is restored instead."""
    path = resolve_checkpoint_dir(path)
    with open(os.path.join(path, _META)) as fh:
        info = json.load(fh)
    if info["backend"] == "orbax":
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        state = ckptr.restore(os.path.join(os.path.abspath(path), "state"),
                              _to_host(template))
    else:
        import flax.serialization
        with open(os.path.join(path, _FLAX_FILE), "rb") as fh:
            state = flax.serialization.from_bytes(template, fh.read())
    # orbax will happily hand back whatever shapes were saved — validate
    # against the template so a wrong-config restore fails loudly here
    t_leaves = jax.tree_util.tree_leaves(_to_host(template))
    s_leaves = jax.tree_util.tree_leaves(state)
    for t, s in zip(t_leaves, s_leaves):
        if tuple(np.shape(t)) != tuple(np.shape(s)):
            raise ValueError(
                f"checkpoint leaf shape {np.shape(s)} does not match the "
                f"template's {np.shape(t)}; was this checkpoint saved for a "
                "different configuration?")
    return state, info["meta"]
