"""Full training-state checkpoints: params + SA λ + optimizer moments.

The reference can only persist the Keras network (``models.py:315-319``) —
its λ weights and Adam/L-BFGS state are silently lost on reload (SURVEY §5),
so "resume" actually restarts the minimax from scratch.  Here the complete
trainable state round-trips:

* primary path: `orbax.checkpoint` ``StandardCheckpointer`` (async-capable,
  sharding-aware — the right tool once states are sharded over a mesh);
* fallback: `flax.serialization` msgpack bytes in a single file (used when
  orbax is unavailable or the state contains objects orbax rejects).

Both are behind the same two functions, keyed by a directory path::

    save_checkpoint(path, state)
    state = restore_checkpoint(path, template)   # template supplies structure

``template`` must be a pytree with the same structure/shapes as the saved
state (build it from a freshly compiled solver, as
``CollocationSolverND.restore_checkpoint`` does).

Crash-safety protocol (what a preemptible environment actually needs):

* every save lands in a ``<path>.tmp`` sibling first, every payload file
  is **fsynced**, and the directory swaps in via atomic renames — a
  process killed at ANY point leaves a restorable checkpoint on disk;
* the previous checkpoint is **retained** at ``<path>.old`` (keep-last
  K=2), not discarded: rollback and corruption fallback both need a
  second intact generation;
* the meta file embeds a **content checksum** over every payload byte;
  :func:`restore_checkpoint` validates it (and the pytree shapes) before
  trusting a generation, and falls back to ``<path>.old`` when the newest
  is torn/corrupt — raising :class:`CheckpointCorrupted` only when no
  generation survives.  ``tests/test_resilience.py`` drives this with
  chaos-torn writes.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Optional

import jax
import numpy as np

from .telemetry import log_event

_META = "tdq_meta.json"
_FLAX_FILE = "state.msgpack"


class TemplateMismatch(ValueError):
    """The caller's template does not match what this checkpoint holds —
    a CONFIG error (wrong layer sizes, different λ setup), not corruption.
    Never absorbed by the previous-generation fallback: silently resuming
    an older run would be worse than the error."""


class CheckpointCorrupted(RuntimeError):
    """No checkpoint generation under this path survived validation.
    ``failures`` maps each candidate directory to why it was rejected."""

    def __init__(self, path: str, failures: dict):
        self.path = path
        self.failures = dict(failures)
        detail = "; ".join(f"{d}: {why}" for d, why in failures.items())
        super().__init__(
            f"every checkpoint generation under {path} failed validation "
            f"({detail})")


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(np.asarray, tree)


def _payload_files(path: str) -> list:
    """Every file under ``path`` except the meta, as sorted relative paths
    — the checksum domain (sorted so the digest is walk-order independent)."""
    out = []
    for root, _, files in os.walk(path):
        for f in files:
            if f == _META:
                continue
            out.append(os.path.relpath(os.path.join(root, f), path))
    return sorted(out)


def _digest_dir(path: str) -> dict:
    """Content checksum over every payload byte (file names included, so a
    missing or renamed file also fails validation)."""
    h = hashlib.sha256()
    files = _payload_files(path)
    for rel in files:
        h.update(rel.encode("utf-8"))
        h.update(b"\x00")
        with open(os.path.join(path, rel), "rb") as fh:
            for block in iter(lambda: fh.read(1 << 20), b""):
                h.update(block)
    return {"algo": "sha256", "digest": h.hexdigest(), "n_files": len(files)}


def _fsync_dir_tree(path: str) -> None:
    """fsync every file under ``path`` plus the directories themselves —
    the rename-based swap below is only crash-atomic if the payload bytes
    reached disk first.  Best-effort on filesystems without dir fsync."""
    for root, dirs, files in os.walk(path):
        for f in files:
            with open(os.path.join(root, f), "rb") as fh:
                os.fsync(fh.fileno())
        try:
            fd = os.open(root, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass


def checkpoint_exists(path: str) -> bool:
    """Is there any restorable generation (current, parked, or previous)
    under ``path``?"""
    path = os.path.abspath(path)
    return any(os.path.exists(os.path.join(c, _META))
               for c in (path, path + ".old"))


def save_checkpoint(path: str, state: dict, meta: dict | None = None,
                    extra_files: dict | None = None) -> None:
    """Write ``state`` (a pytree dict) under directory ``path``.

    ``meta`` is an optional JSON-serialisable dict stored alongside (losses
    history, iteration counters, …).

    ``extra_files`` maps checkpoint-relative paths to raw ``bytes`` written
    alongside the state (e.g. the fleet layer's serialized AOT programs,
    ``aot/u_256.bin``).  They land in the same ``.tmp`` staging directory
    BEFORE the content checksum is computed, so they ride the full
    crash-safety protocol: fsynced, checksummed, atomically swapped, and
    validated by :func:`restore_checkpoint` exactly like the state payload
    — a torn AOT blob fails the whole generation instead of silently
    serving a corrupt program.

    The write is crash-safe: everything lands in a ``<path>.tmp`` sibling
    first (payloads fsynced, content checksum embedded in the meta), then
    swaps in via directory renames.  A process killed at ANY point leaves
    a restorable checkpoint on disk — either the new one, or the previous
    one (parked at ``<path>.old``, which :func:`restore_checkpoint` falls
    back to).  The parked generation is KEPT (last K=2): it is both the
    mid-swap-kill safety net and the fallback when the newest generation
    is later found corrupt.  This matters because the mid-run checkpoint
    hook (``fit(checkpoint_dir=)``) exists precisely for environments that
    kill processes at arbitrary moments; an overwrite-in-place would put
    the only resume point in the blast radius of every periodic save.
    """
    import shutil

    path = os.path.abspath(path)
    tmp, old = path + ".tmp", path + ".old"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    state = _to_host(state)
    backend = "flax"
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(tmp, "state"), state)
        ckptr.wait_until_finished()
        backend = "orbax"
    except Exception:
        import flax.serialization
        with open(os.path.join(tmp, _FLAX_FILE), "wb") as fh:
            fh.write(flax.serialization.to_bytes(state))
    for rel, blob in (extra_files or {}).items():
        rel = os.path.normpath(rel)
        if os.path.isabs(rel) or rel.startswith(".."):
            raise ValueError(f"extra file path {rel!r} escapes the "
                             "checkpoint directory")
        if os.path.basename(rel) == _META:
            raise ValueError(f"extra file {rel!r} would shadow the "
                             "checkpoint meta")
        dest = os.path.join(tmp, rel)
        os.makedirs(os.path.dirname(dest) or tmp, exist_ok=True)
        with open(dest, "wb") as fh:
            fh.write(bytes(blob))
    with open(os.path.join(tmp, _META), "w") as fh:
        json.dump({"backend": backend, "meta": meta or {},
                   # restores compare these against the caller's template
                   # BEFORE any backend load, so a wrong-config restore is
                   # diagnosed as TemplateMismatch (and never triggers the
                   # corruption fallback) regardless of which backend error
                   # a mismatched deserialisation would otherwise raise
                   "leaf_shapes": [list(np.shape(leaf)) for leaf in
                                   jax.tree_util.tree_leaves(state)],
                   "checksum": _digest_dir(tmp)}, fh)
        fh.flush()
        os.fsync(fh.fileno())
    _fsync_dir_tree(tmp)
    # swap: park the previous checkpoint, promote the new one, KEEP the
    # parked copy (K=2).  Both renames are atomic on POSIX.  Only clear a
    # stale ``.old`` when there is a current ``path`` to park in its place:
    # if a prior save died mid-swap, ``.old`` holds the ONLY restorable
    # checkpoint until the rename below promotes ``tmp``.
    if os.path.exists(path):
        shutil.rmtree(old, ignore_errors=True)
        os.rename(path, old)
    os.rename(tmp, path)
    from .resilience.chaos import active_chaos
    c = active_chaos()
    if c is not None:
        c.on_checkpoint_saved(path)


def resolve_checkpoint_dir(path: str) -> str:
    """The directory a restore should actually read: ``path`` itself, or
    the parked ``<path>.old`` when a killed save left only that (callers
    that peek at ``tdq_meta.json`` themselves must use this too)."""
    path = os.path.abspath(path)
    if not os.path.exists(os.path.join(path, _META)) \
            and os.path.exists(os.path.join(path + ".old", _META)):
        return path + ".old"
    return path


def verify_checkpoint(path: str) -> None:
    """Validate one checkpoint directory's content checksum against its
    meta.  Raises ``ValueError`` on mismatch (and ``OSError`` when files
    are missing); checkpoints from before the checksum era pass (nothing
    recorded to validate against)."""
    with open(os.path.join(path, _META)) as fh:
        info = json.load(fh)
    want = info.get("checksum")
    if want is None:
        return
    got = _digest_dir(path)
    if got["digest"] != want.get("digest"):
        raise ValueError(
            f"content checksum mismatch ({got['n_files']} files, "
            f"{got['digest'][:12]}… != recorded {str(want.get('digest'))[:12]}…)"
            " — torn or corrupted write")


def _template_shape_check(saved_shapes, template) -> None:
    t_shapes = [tuple(np.shape(leaf))
                for leaf in jax.tree_util.tree_leaves(_to_host(template))]
    saved = [tuple(s) for s in saved_shapes]
    if len(saved) != len(t_shapes):
        raise TemplateMismatch(
            f"checkpoint has {len(saved)} array leaves but the template "
            f"has {len(t_shapes)}; was this checkpoint saved for a "
            "different configuration?")
    for t, s in zip(t_shapes, saved):
        if t != s:
            raise TemplateMismatch(
                f"checkpoint leaf shape {s} does not match the template's "
                f"{t}; was this checkpoint saved for a different "
                "configuration?")


def _restore_one(path: str, template: dict) -> tuple[dict, dict]:
    """Load + validate a single checkpoint directory (no fallback)."""
    verify_checkpoint(path)
    with open(os.path.join(path, _META)) as fh:
        info = json.load(fh)
    if "leaf_shapes" in info:
        # config-vs-corruption triage BEFORE the backend load: a template
        # that cannot match raises TemplateMismatch here, so a backend
        # deserialisation error below really does mean a damaged payload
        _template_shape_check(info["leaf_shapes"], template)
    if info["backend"] == "orbax":
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        state = ckptr.restore(os.path.join(os.path.abspath(path), "state"),
                              _to_host(template))
    else:
        import flax.serialization
        with open(os.path.join(path, _FLAX_FILE), "rb") as fh:
            state = flax.serialization.from_bytes(template, fh.read())
    # orbax will happily hand back whatever shapes were saved — validate
    # against the template so a wrong-config restore fails loudly here
    # (covers pre-leaf_shapes-era checkpoints; newer ones were already
    # triaged above)
    _template_shape_check([np.shape(s) for s in
                           jax.tree_util.tree_leaves(state)], template)
    return state, info["meta"]


def restore_checkpoint(path: str, template: dict) -> tuple[dict, dict]:
    """Load the state saved under ``path``.  ``template`` provides the pytree
    structure (and, for the orbax path, shape/dtype guidance).  Returns
    ``(state, meta)``.

    Restore order: the current generation, then the parked previous one
    (``<path>.old`` — present after a killed mid-swap save AND, since the
    keep-last-2 protocol, after every completed save).  A generation whose
    content checksum fails, whose files are missing, or whose payload
    cannot be deserialised is skipped with a logged warning instead of
    crashing the restore; only when NO generation survives does
    :class:`CheckpointCorrupted` raise.

    Template-shape mismatches are NOT absorbed by the fallback: the newest
    generation deserialising cleanly into the wrong shapes means the
    caller compiled a different configuration — that error propagates
    (falling back would silently resume from an older run).
    """
    path = os.path.abspath(path)
    candidates = [c for c in (path, path + ".old")
                  if os.path.exists(os.path.join(c, _META))]
    if not candidates:
        raise FileNotFoundError(
            f"no checkpoint meta under {path} (or its .old sibling)")
    failures: dict = {}
    for i, cand in enumerate(candidates):
        try:
            state, meta = _restore_one(cand, template)
        except TemplateMismatch:
            raise  # config mismatch, not corruption — never fall back
        except Exception as e:
            failures[cand] = f"{type(e).__name__}: {e}"
        else:
            if i > 0 or failures:
                log_event("checkpoint",
                          f"restored the previous generation {cand} "
                          f"(newer one rejected: "
                          f"{'; '.join(failures.values()) or 'missing'})",
                          level="warning", verbose=True, restored=cand,
                          failures=failures)
            return state, meta
    raise CheckpointCorrupted(path, failures)
