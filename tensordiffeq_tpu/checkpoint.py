"""Full training-state checkpoints: params + SA λ + optimizer moments.

The reference can only persist the Keras network (``models.py:315-319``) —
its λ weights and Adam/L-BFGS state are silently lost on reload (SURVEY §5),
so "resume" actually restarts the minimax from scratch.  Here the complete
trainable state round-trips:

* primary path: `orbax.checkpoint` ``StandardCheckpointer`` (async-capable,
  sharding-aware — the right tool once states are sharded over a mesh);
* fallback: `flax.serialization` msgpack bytes in a single file (used when
  orbax is unavailable or the state contains objects orbax rejects).

Both are behind the same two functions, keyed by a directory path::

    save_checkpoint(path, state)
    state = restore_checkpoint(path, template)   # template supplies structure

``template`` must be a pytree with the same structure/shapes as the saved
state (build it from a freshly compiled solver, as
``CollocationSolverND.restore_checkpoint`` does).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np

_META = "tdq_meta.json"
_FLAX_FILE = "state.msgpack"


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(np.asarray, tree)


def save_checkpoint(path: str, state: dict, meta: dict | None = None) -> None:
    """Write ``state`` (a pytree dict) under directory ``path``.

    ``meta`` is an optional JSON-serialisable dict stored alongside (losses
    history, iteration counters, …).
    """
    os.makedirs(path, exist_ok=True)
    state = _to_host(state)
    backend = "flax"
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        target = os.path.join(os.path.abspath(path), "state")
        # orbax refuses to overwrite; emulate standard resume semantics
        if os.path.exists(target):
            import shutil
            shutil.rmtree(target)
        ckptr.save(target, state)
        ckptr.wait_until_finished()
        backend = "orbax"
    except Exception:
        import flax.serialization
        with open(os.path.join(path, _FLAX_FILE), "wb") as fh:
            fh.write(flax.serialization.to_bytes(state))
    with open(os.path.join(path, _META), "w") as fh:
        json.dump({"backend": backend, "meta": meta or {}}, fh)


def restore_checkpoint(path: str, template: dict) -> tuple[dict, dict]:
    """Load the state saved under ``path``.  ``template`` provides the pytree
    structure (and, for the orbax path, shape/dtype guidance).  Returns
    ``(state, meta)``."""
    with open(os.path.join(path, _META)) as fh:
        info = json.load(fh)
    if info["backend"] == "orbax":
        import orbax.checkpoint as ocp
        ckptr = ocp.StandardCheckpointer()
        state = ckptr.restore(os.path.join(os.path.abspath(path), "state"),
                              _to_host(template))
    else:
        import flax.serialization
        with open(os.path.join(path, _FLAX_FILE), "rb") as fh:
            state = flax.serialization.from_bytes(template, fh.read())
    # orbax will happily hand back whatever shapes were saved — validate
    # against the template so a wrong-config restore fails loudly here
    t_leaves = jax.tree_util.tree_leaves(_to_host(template))
    s_leaves = jax.tree_util.tree_leaves(state)
    for t, s in zip(t_leaves, s_leaves):
        if tuple(np.shape(t)) != tuple(np.shape(s)):
            raise ValueError(
                f"checkpoint leaf shape {np.shape(s)} does not match the "
                f"template's {np.shape(t)}; was this checkpoint saved for a "
                "different configuration?")
    return state, info["meta"]
