"""Initial & boundary condition declarations.

Capability parity with the reference ``tensordiffeq/boundaries.py`` class
family — ``IC`` (:163), ``dirichletBC`` (:41), ``FunctionDirichletBC`` (:62),
``FunctionNeumannBC`` (:103), ``periodicBC`` (:205) — re-designed for a
functional JAX solver:

* All face meshes and target values are assembled **once, host-side, in
  NumPy** at construction (same as the reference's eager ``create_input``),
  then become jit-time constants.  Nothing here traces.
* Derivative-carrying conditions (periodic, Neumann) hold *JAX-style* user
  functions ``deriv_model(u, *coords)`` operating on a scalar point function
  ``u`` (see :mod:`tensordiffeq_tpu.ops.derivatives`); the solver vmaps them
  over face points.  This replaces the reference's batched ``tf.gradients``
  closures (``boundaries.py:211,111``).
* Sub-sampling (``n_values``) takes an explicit ``seed`` instead of global
  NumPy RNG state.

Each condition exposes a uniform contract consumed by the loss assembler
(:mod:`tensordiffeq_tpu.models.collocation`):

* value-matching conditions (``IC``/``dirichletBC``/``FunctionDirichletBC``):
  ``.input`` — ``[n, ndim]`` points, ``.val`` — ``[n, n_out]`` targets.
* ``periodicBC``: ``.upper``/``.lower`` — per-variable ``[n, ndim]`` meshes
  and ``.deriv_model`` — per-variable derivative tuples to match.
* ``FunctionNeumannBC``: ``.input`` per-variable meshes, ``.val`` targets and
  ``.deriv_model`` producing the constrained derivative.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from .domains import DomainND
from .ops.meshes import grid_points


def _eval_on_mesh_columns(domain: DomainND, mesh: np.ndarray,
                          funs: Sequence[Callable],
                          func_inputs: Sequence[Sequence[str]]) -> np.ndarray:
    """Evaluate target functions on the face mesh's own coordinate columns.

    Each function gets the mesh columns named in its ``func_inputs`` entry,
    guaranteeing row-alignment between every target value and its face point
    (evaluating on an independently-built grid — as the reference does in
    ``boundaries.py:92-101`` — silently misaligns whenever the requested
    input order differs from domain declaration order).  Returns ``[n, n_out]``
    with one column per function.
    """
    n = mesh.shape[0]
    cols = []
    for f, names in zip(funs, func_inputs):
        args = [mesh[:, domain.var_index(v)] for v in names]
        v = np.ravel(np.asarray(f(*args)))
        if v.size == 1:
            v = np.full(n, float(v))
        elif v.size != n:
            raise ValueError(
                f"Boundary target function returned {v.size} values for a "
                f"{n}-point face mesh")
        cols.append(v.reshape(-1, 1))
    return np.concatenate(cols, axis=1)


class BC:
    """Base boundary/initial condition (reference ``boundaries.py:12-38``)."""

    isPeriodic = False
    isInit = False
    isNeumann = False
    isDirichlect = False  # reference spelling kept for familiarity
    isDirichlet = False

    def __init__(self, domain: DomainND):
        self.domain = domain

    # -- shared mesh builders ----------------------------------------------
    def _face_points(self, var: str, value: float) -> np.ndarray:
        """Tensor-product mesh over all variables except ``var``, with the
        ``var`` column pinned to ``value`` (the domain-face mesh the reference
        builds in ``create_input``, ``boundaries.py:54-59``)."""
        others = [v for v in self.domain.vars if v != var]
        mesh = grid_points([self.domain.linspace(v) for v in others])
        col = np.full((mesh.shape[0], 1), float(value))
        return np.insert(mesh, self.domain.var_index(var), col.ravel(), axis=1)

    def _subsample(self, arrays: Sequence[np.ndarray], n_values: Optional[int],
                   seed: Optional[int]) -> list[np.ndarray]:
        """Optionally pick ``n_values`` common random rows from each array
        (reference ``n_values`` / ``self.nums`` logic, ``boundaries.py:88-90``)."""
        if n_values is None:
            return list(arrays)
        rng = np.random.RandomState(seed)
        idx = rng.randint(0, arrays[0].shape[0], size=n_values)
        return [a[idx] for a in arrays]


class dirichletBC(BC):
    """Constant-value Dirichlet condition on one domain face
    (reference ``boundaries.py:41-59``).

    ``target`` is ``"upper"`` or ``"lower"`` — which face of variable ``var``.
    """

    isDirichlect = isDirichlet = True

    def __init__(self, domain: DomainND, val: float, var: str, target: str):
        super().__init__(domain)
        if target not in ("upper", "lower"):
            raise ValueError(f"target must be 'upper'/'lower', got {target!r}")
        self.var = var
        self.target = target
        lo, hi = domain.bounds(var)
        self.face_value = hi if target == "upper" else lo
        self.input = self._face_points(var, self.face_value)
        self.val = np.full((self.input.shape[0], 1), float(val))


class FunctionDirichletBC(BC):
    """Dirichlet condition whose target values come from user functions of the
    face coordinates (reference ``boundaries.py:62-101``).

    ``fun``: list of functions (one per network output); ``func_inputs``: for
    each function, the list of variable names it takes (vectorised NumPy).
    """

    isDirichlect = isDirichlet = True

    def __init__(self, domain: DomainND, fun: Sequence[Callable], var: str,
                 target: str, func_inputs: Sequence[Sequence[str]],
                 n_values: Optional[int] = None, seed: Optional[int] = None):
        super().__init__(domain)
        self.var = var
        self.target = target
        lo, hi = domain.bounds(var)
        self.face_value = hi if target == "upper" else lo
        mesh = self._face_points(var, self.face_value)
        # Evaluate target functions on the face mesh's OWN columns so values
        # stay row-aligned with the points regardless of func_inputs order.
        val = _eval_on_mesh_columns(domain, mesh, fun, func_inputs)
        self.input, self.val = self._subsample([mesh, val], n_values, seed)


class IC(BC):
    """Initial condition at ``t = lower bound of the time variable``
    (reference ``boundaries.py:163-202``; note the reference pins ``t=0.0``
    regardless of the declared range — we pin the declared lower bound, which
    matches every shipped example).

    ``fun``: list of initial-profile functions, one per network output;
    ``var``: for each function, the list of spatial variable names it takes.
    """

    isInit = True

    def __init__(self, domain: DomainND, fun: Sequence[Callable],
                 var: Sequence[Sequence[str]], n_values: Optional[int] = None,
                 seed: Optional[int] = None):
        super().__init__(domain)
        if domain.time_var is None:
            raise ValueError("IC requires a domain with time_var set")
        self.fun = list(fun)
        self.vars = [list(v) for v in var]
        t0 = domain.bounds(domain.time_var)[0]
        mesh = self._face_points(domain.time_var, t0)
        val = _eval_on_mesh_columns(domain, mesh, self.fun, self.vars)
        self.input, self.val = self._subsample([mesh, val], n_values, seed)


class periodicBC(BC):
    """Periodic condition matching the solution (and any user-requested
    derivatives) between the upper and lower faces of each listed variable
    (reference ``boundaries.py:205-249``).

    ``deriv_model``: one JAX-style function per variable,
    ``deriv_model(u, *coords) -> tuple`` evaluated at a single point; every
    element of the returned tuple is matched upper-vs-lower.  (The reference
    intends the same but its nested index loop only ever matches the first
    element, ``models.py:143-149``; we match all — the SA-PINN paper's
    formulation.)
    """

    isPeriodic = True

    def __init__(self, domain: DomainND, var: Sequence[str],
                 deriv_model: Sequence[Callable], n_values: Optional[int] = None,
                 seed: Optional[int] = None):
        super().__init__(domain)
        self.var = list(var)
        self.deriv_model = list(deriv_model)
        self.upper: list[np.ndarray] = []
        self.lower: list[np.ndarray] = []
        for v in self.var:
            lo, hi = domain.bounds(v)
            up, low = self._subsample(
                [self._face_points(v, hi), self._face_points(v, lo)],
                n_values, seed)
            self.upper.append(up)
            self.lower.append(low)


class FunctionNeumannBC(BC):
    """Neumann condition: a user-selected derivative of the solution on one
    face equals function-valued targets (reference ``boundaries.py:103-160``).

    One ``(fun[i], deriv_model[i])`` pair per variable in ``var``: the
    derivative computed by ``deriv_model[i]`` on variable ``i``'s face is
    constrained to ``fun[i]`` evaluated on that same face mesh (if
    ``deriv_model[i]`` returns a tuple, every component is constrained to
    that target).  ``self.input`` and ``self.val`` are per-variable lists,
    row-aligned mesh-by-mesh.
    """

    isNeumann = True

    def __init__(self, domain: DomainND, fun: Sequence[Callable],
                 var: Sequence[str], target: str,
                 deriv_model: Sequence[Callable],
                 func_inputs: Sequence[Sequence[str]],
                 n_values: Optional[int] = None, seed: Optional[int] = None):
        super().__init__(domain)
        self.var = list(var)
        self.target = target
        self.deriv_model = list(deriv_model)
        if not (len(fun) == len(self.var) == len(self.deriv_model)
                == len(func_inputs)):
            raise ValueError(
                "FunctionNeumannBC needs one fun / deriv_model / func_inputs "
                f"entry per variable; got {len(fun)}/{len(self.deriv_model)}/"
                f"{len(func_inputs)} for {len(self.var)} variables")

        self.input: list[np.ndarray] = []
        self.val: list[np.ndarray] = []
        for v, f, names in zip(self.var, fun, func_inputs):
            lo, hi = domain.bounds(v)
            face = hi if target == "upper" else lo
            mesh = self._face_points(v, face)
            val = _eval_on_mesh_columns(domain, mesh, [f], [names])
            mesh, val = self._subsample([mesh, val], n_values, seed)
            self.input.append(mesh)
            self.val.append(val)
