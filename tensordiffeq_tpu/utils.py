"""Shared utilities.

Parity layer for the reference's grab-bag ``tensordiffeq/utils.py``, minus
what JAX makes native:

* flat-vector param packing (``get_weights``/``set_weights``/``get_sizes``,
  reference ``utils.py:7-35``) → :func:`jax.flatten_util.ravel_pytree`;
* ``tf.constant``/``convertTensor``/``tensor`` casts → thin jnp aliases;
* SA-weight initialisation (``initialize_weights_loss``, ``utils.py:102-115``)
  → :func:`initialize_lambdas`, which builds the λ *pytree* consumed by the
  solver (a dict of per-term vectors / ``None``), not a flat list + index map.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from .ops.losses import MSE, g_MSE  # re-export for parity  # noqa: F401
from .sampling import LatinHypercubeSample  # noqa: F401


def constant(val, dtype=jnp.float32):
    """Parity: reference ``utils.py:51-52``."""
    return jnp.asarray(val, dtype=dtype)


def convertTensor(val, dtype=jnp.float32):
    """Parity: reference ``utils.py:55-56``."""
    return jnp.asarray(val, dtype=dtype)


def tensor(x, dtype=jnp.float32):
    """Parity: reference ``utils.py:68-69``."""
    return jnp.asarray(x, dtype=dtype)


def get_weights(params) -> jnp.ndarray:
    """Flatten a parameter pytree to one vector (reference ``utils.py:20-29``;
    here a one-liner thanks to ``ravel_pytree``)."""
    flat, _ = ravel_pytree(params)
    return flat


def set_weights(params_template, flat: jnp.ndarray):
    """Rebuild a parameter pytree from a flat vector using the template's
    structure (reference ``utils.py:7-17``)."""
    _, unravel = ravel_pytree(params_template)
    return unravel(flat)


def get_sizes(layer_sizes):
    """Per-layer weight/bias sizes (reference ``utils.py:32-35``); retained
    for API familiarity, rarely needed in JAX."""
    sizes_w = [layer_sizes[i] * layer_sizes[i - 1]
               for i in range(1, len(layer_sizes))]
    sizes_b = list(layer_sizes[1:])
    return sizes_w, sizes_b


def initialize_lambdas(init_weights: Optional[dict], dict_adaptive: Optional[dict]
                       ) -> dict[str, list[Optional[jnp.ndarray]]]:
    """Build the self-adaptive λ pytree from the user's ``init_weights`` /
    ``dict_adaptive`` contract (reference ``utils.py:102-115`` +
    ``models.py:95-105``).

    Returns ``{"residual": [λ|None, ...], "BCs": [λ|None, ...]}`` with one
    entry per loss term, ``None`` where the term is non-adaptive.  Unlike the
    reference's flat list + index map (whose shared-index bug for multiple
    adaptive residuals is catalogued in SURVEY §2.4.4), λ position is
    structural — no index arithmetic exists to go wrong.
    """
    lambdas: dict[str, list[Optional[jnp.ndarray]]] = {"residual": [], "BCs": []}
    if init_weights is None or dict_adaptive is None:
        return lambdas
    for key in ("residual", "BCs"):
        flags = dict_adaptive.get(key, [])
        inits = init_weights.get(key, [])
        if len(flags) != len(inits):
            raise ValueError(
                f"dict_adaptive[{key!r}] and init_weights[{key!r}] must have "
                f"the same length, got {len(flags)} vs {len(inits)}")
        for flag, init in zip(flags, inits):
            if flag and init is None:
                raise ValueError(
                    f"Loss term in {key!r} marked adaptive but init weight is None")
            if not flag:
                lambdas[key].append(None)
                continue
            lam = jnp.asarray(init, dtype=jnp.float32)
            # normalise per-point weight vectors to column shape [n, 1]: a
            # 1-D (n,) λ would silently broadcast against (n, 1) errors into
            # an (n, n) outer product inside MSE
            if lam.ndim == 1 and lam.shape[0] > 1:
                lam = lam.reshape(-1, 1)
            lambdas[key].append(lam)
    return lambdas


def tree_copy(tree: Any) -> Any:
    """Deep-copy a pytree of arrays (the reference's best-model tracking
    aliases instead of copying — SURVEY §2.4.6; this is the fix)."""
    return jax.tree_util.tree_map(jnp.array, tree)


def to_numpy(tree: Any) -> Any:
    return jax.tree_util.tree_map(np.asarray, tree)
