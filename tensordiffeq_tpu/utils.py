"""Shared utilities.

Parity layer for the reference's grab-bag ``tensordiffeq/utils.py``, minus
what JAX makes native:

* flat-vector param packing (``get_weights``/``set_weights``/``get_sizes``,
  reference ``utils.py:7-35``) → :func:`jax.flatten_util.ravel_pytree`;
* ``tf.constant``/``convertTensor``/``tensor`` casts → thin jnp aliases;
* SA-weight initialisation (``initialize_weights_loss``, ``utils.py:102-115``)
  → :func:`initialize_lambdas`, which builds the λ *pytree* consumed by the
  solver (a dict of per-term vectors / ``None``), not a flat list + index map.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from .ops.losses import MSE, g_MSE  # re-export for parity  # noqa: F401
from .ops.meshes import flatten_and_stack, multimesh  # noqa: F401
from .sampling import LatinHypercubeSample  # noqa: F401


_compile_cache_dir: Optional[str] = None
_compile_cache_wired = False


def enable_compilation_cache(path: Optional[str] = None) -> Optional[str]:
    """Enable JAX's persistent compilation cache (idempotent).

    Every process start otherwise pays full XLA compile cost — the round-3
    head-to-head lost ~100 s of time-to-first-accuracy to compiles, and
    each TPU tunnel window burns minutes recompiling programs it already
    compiled the window before.  A disk cache keyed on (program, backend)
    makes warm starts skip that entirely.

    Resolution order: explicit ``path`` arg > ``TDQ_COMPILE_CACHE`` env
    (``0``/``off`` disables) > a per-user dir under the system temp dir.
    Called automatically by ``CollocationSolverND.compile`` /
    ``DiscoveryModel.compile``; safe to call repeatedly or before backend
    init.  Returns the cache dir in use, or ``None`` when disabled.

    **CPU backend: the cache stays OFF unless explicitly requested**
    (``path`` arg or ``TDQ_COMPILE_CACHE=<dir>``).  Two measured reasons
    (PR 5).  Correctness: with the shared default dir, a cold-cache
    ``pytest tests/test_checkpoint.py`` failed its sharded-resume
    trajectory check (max rel diff 0.49 after 20 toy SA steps) while the
    same run passed with the cache off or warm — cache-served executables
    can differ from fresh compiles at a level the minimax amplifies, and
    WHICH programs get cached depends on the 0.5 s compile-time threshold,
    i.e. on machine load.  Concurrency: tier-1 and a CPU-fallback bench
    sharing ``/tmp/tdq_xla_cache_*`` were observed garbaging each other's
    numerics (PR-4 note: 0.0 min_loss / 1.6 rel-L2).  CPU compiles here
    cost seconds, so the cache bought little on that backend anyway; TPU
    (where a tunnel-window compile costs minutes and processes are
    serialized by the tunnel) keeps the shared cache.
    """
    global _compile_cache_dir, _compile_cache_wired
    env = os.environ.get("TDQ_COMPILE_CACHE", "")
    if env.lower() in ("0", "off", "false", "none"):
        return None
    if path is None:
        if _compile_cache_wired:  # auto-call must never clobber an earlier
            return _compile_cache_dir  # explicit enable_compilation_cache(p)
        already = getattr(jax.config, "jax_compilation_cache_dir", None)
        if already:  # ... nor a user-configured jax cache dir
            _compile_cache_dir, _compile_cache_wired = already, True
            return already
        if not env:
            try:
                backend = jax.default_backend()
            except Exception:
                backend = None
            if backend == "cpu":
                return None  # see docstring: correctness over warm starts
        uid = getattr(os, "getuid", lambda: "")()
        path = env or os.path.join(tempfile.gettempdir(),
                                   f"tdq_xla_cache_{uid}")
    if _compile_cache_wired and path == _compile_cache_dir:
        return _compile_cache_dir
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache any program over 0.5 s of compile: the train-step programs
        # (seconds on CPU, minutes through a TPU tunnel) all clear it, while
        # trivial executables stay out (XLA's CPU AOT loader logs two
        # machine-feature lines per loaded entry — caching hundreds of tiny
        # programs would drown stderr for no win)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        return None  # old jax / unsupported backend: run uncached
    _compile_cache_dir = path
    _compile_cache_wired = True
    return path


def constant(val, dtype=jnp.float32):
    """Parity: reference ``utils.py:51-52``."""
    return jnp.asarray(val, dtype=dtype)


def convertTensor(val, dtype=jnp.float32):
    """Parity: reference ``utils.py:55-56``."""
    return jnp.asarray(val, dtype=dtype)


def tensor(x, dtype=jnp.float32):
    """Parity: reference ``utils.py:68-69``."""
    return jnp.asarray(x, dtype=dtype)


def get_weights(params) -> jnp.ndarray:
    """Flatten a parameter pytree to one vector (reference ``utils.py:20-29``;
    here a one-liner thanks to ``ravel_pytree``)."""
    flat, _ = ravel_pytree(params)
    return flat


def set_weights(params_template, flat: jnp.ndarray):
    """Rebuild a parameter pytree from a flat vector using the template's
    structure (reference ``utils.py:7-17``)."""
    _, unravel = ravel_pytree(params_template)
    return unravel(flat)


def get_sizes(layer_sizes):
    """Per-layer weight/bias sizes (reference ``utils.py:32-35``); retained
    for API familiarity, rarely needed in JAX."""
    sizes_w = [layer_sizes[i] * layer_sizes[i - 1]
               for i in range(1, len(layer_sizes))]
    sizes_b = list(layer_sizes[1:])
    return sizes_w, sizes_b


def initialize_lambdas(init_weights: Optional[dict], dict_adaptive: Optional[dict]
                       ) -> dict[str, list[Optional[jnp.ndarray]]]:
    """Build the self-adaptive λ pytree from the user's ``init_weights`` /
    ``dict_adaptive`` contract (reference ``utils.py:102-115`` +
    ``models.py:95-105``).

    Returns ``{"residual": [λ|None, ...], "BCs": [λ|None, ...]}`` with one
    entry per loss term, ``None`` where the term is non-adaptive.  Unlike the
    reference's flat list + index map (whose shared-index bug for multiple
    adaptive residuals is catalogued in SURVEY §2.4.4), λ position is
    structural — no index arithmetic exists to go wrong.
    """
    lambdas: dict[str, list[Optional[jnp.ndarray]]] = {"residual": [], "BCs": []}
    if init_weights is None or dict_adaptive is None:
        return lambdas
    for key in ("residual", "BCs"):
        flags = dict_adaptive.get(key, [])
        inits = init_weights.get(key, [])
        if len(flags) != len(inits):
            raise ValueError(
                f"dict_adaptive[{key!r}] and init_weights[{key!r}] must have "
                f"the same length, got {len(flags)} vs {len(inits)}")
        for flag, init in zip(flags, inits):
            if flag and init is None:
                raise ValueError(
                    f"Loss term in {key!r} marked adaptive but init weight is None")
            if not flag:
                lambdas[key].append(None)
                continue
            lam = jnp.asarray(init, dtype=jnp.float32)
            # normalise per-point weight vectors to column shape [n, 1]: a
            # 1-D (n,) λ would silently broadcast against (n, 1) errors into
            # an (n, n) outer product inside MSE
            if lam.ndim == 1 and lam.shape[0] > 1:
                lam = lam.reshape(-1, 1)
            lambdas[key].append(lam)
    return lambdas


def tree_copy(tree: Any) -> Any:
    """Deep-copy a pytree of arrays (the reference's best-model tracking
    aliases instead of copying — SURVEY §2.4.6; this is the fix)."""
    return jax.tree_util.tree_map(jnp.array, tree)


def to_numpy(tree: Any) -> Any:
    return jax.tree_util.tree_map(np.asarray, tree)
