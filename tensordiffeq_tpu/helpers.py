"""Accuracy metrics (parity: reference ``tensordiffeq/helpers.py``)."""

from __future__ import annotations

import numpy as np


def find_L2_error(u_pred, u_star) -> float:
    """Relative L2 error ``||u*-u_pred||/||u*||`` — the accuracy metric used
    by every reference example (``helpers.py:3-4``)."""
    u_pred = np.asarray(u_pred).ravel()
    u_star = np.asarray(u_star).ravel()
    return float(np.linalg.norm(u_star - u_pred, 2) / np.linalg.norm(u_star, 2))
