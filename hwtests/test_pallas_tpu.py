"""On-hardware pallas parity: the Mosaic-compiled Taylor-table kernels must
match the XLA engine numerically, forward AND backward, on a real TPU.

Interpret-mode CI (``tests/test_pallas.py``) cannot catch hardware-only
failures — round 2 found three: ``scatter`` has no Mosaic lowering (the
one-hot derivative seeds), the batched ``[C, N, in] @ W`` weight-cotangent
transpose is a double contraction ``tpu.matmul`` rejects, and the backward
kernel's VJP residuals overflow the ~16 MB scoped-VMEM budget at the
forward tile size.  These tests pin all three fixes at the AC headline
shape (2-128x4-1, the reference ``examples/AC-SA.py`` network).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensordiffeq_tpu.ops import pallas_taylor
from tensordiffeq_tpu.ops.taylor import taylor_derivatives

pytestmark = pytest.mark.skipif(
    not pallas_taylor.available(),
    reason="real TPU backend required (pallas Mosaic path)")

PREC = jax.lax.Precision.HIGHEST
SHAPES = [(2, 128), (128, 128), (128, 128), (128, 128), (128, 1)]
REQS = {(1,), (0, 0)}  # u_t, u_xx — the Allen-Cahn request set


def _setup(n=2500, seed=0):
    rng = np.random.RandomState(seed)
    layers = [(jnp.asarray(rng.randn(i, o) / np.sqrt(i), jnp.float32),
               jnp.asarray(rng.randn(o) * 0.01, jnp.float32))
              for i, o in SHAPES]
    X = jnp.asarray(rng.rand(n, 2), jnp.float32)
    return layers, X


def test_forward_matches_xla_on_tpu():
    layers, X = _setup()
    fn = pallas_taylor.build_pallas_table_fn(REQS, SHAPES, precision=PREC)
    out = fn(layers, X)
    ref = taylor_derivatives(layers, X, REQS | {()}, precision=PREC)
    for mi in out:
        np.testing.assert_allclose(np.asarray(out[mi]), np.asarray(ref[mi]),
                                   rtol=1e-5, atol=1e-6)


def test_backward_matches_xla_on_tpu():
    layers, X = _setup()
    keys = sorted(REQS | {()})
    fn = pallas_taylor.build_pallas_table_fn(REQS, SHAPES, precision=PREC)

    def loss_pl(ls):
        t = fn(ls, X)
        return sum(jnp.sum(t[k] ** 2) for k in keys)

    def loss_ref(ls):
        t = taylor_derivatives(ls, X, REQS | {()}, precision=PREC)
        return sum(jnp.sum(t[k] ** 2) for k in keys)

    g_pl = jax.grad(loss_pl)(layers)
    g_ref = jax.grad(loss_ref)(layers)
    for (gW, gb), (rW, rb) in zip(g_pl, g_ref):
        scale = float(jnp.max(jnp.abs(rW))) + 1e-8
        assert float(jnp.max(jnp.abs(gW - rW))) / scale < 1e-5
        scale = float(jnp.max(jnp.abs(rb))) + 1e-8
        assert float(jnp.max(jnp.abs(gb - rb))) / scale < 1e-5


def test_point_cotangent_matches_on_tpu():
    """dX through the table (collocation-point adaptation path)."""
    layers, X = _setup(n=300)
    keys = sorted(REQS | {()})
    fn = pallas_taylor.build_pallas_table_fn(REQS, SHAPES, precision=PREC)

    def loss_pl(Xv):
        t = fn(layers, Xv)
        return sum(jnp.sum(t[k] ** 2) for k in keys)

    def loss_ref(Xv):
        t = taylor_derivatives(layers, Xv, REQS | {()}, precision=PREC)
        return sum(jnp.sum(t[k] ** 2) for k in keys)

    gX = jax.grad(loss_pl)(X)
    rX = jax.grad(loss_ref)(X)
    scale = float(jnp.max(jnp.abs(rX))) + 1e-8
    assert float(jnp.max(jnp.abs(gX - rX))) / scale < 1e-5


def test_bf16_kernel_matches_bf16_xla_on_tpu():
    """The mixed-precision kernel (bf16 matmul operands, f32 accumulation
    — the ``fused_dtype="bfloat16"`` MXU path behind ``bench.py
    --precision``'s bf16-pallas config) must agree with the XLA Taylor
    engine under the SAME precision policy: this isolates kernel
    correctness from bf16 truncation.  A loose f32 cross-check bounds the
    truncation itself."""
    layers, X = _setup()
    keys = sorted(REQS | {()})
    fn = pallas_taylor.build_pallas_table_fn(REQS, SHAPES, precision=PREC,
                                             compute_dtype=jnp.bfloat16)
    out = fn(layers, X)
    ref16 = taylor_derivatives(layers, X, REQS | {()}, precision=PREC,
                               compute_dtype=jnp.bfloat16)
    ref32 = taylor_derivatives(layers, X, REQS | {()}, precision=PREC)
    for mi in keys:
        o, r16, r32 = (np.asarray(out[mi]), np.asarray(ref16[mi]),
                       np.asarray(ref32[mi]))
        # same-policy engines: differences only from reduction/fusion order
        scale = np.abs(r16).max() + 1e-8
        assert np.abs(o - r16).max() / scale < 5e-3, mi
        # bf16 truncation vs f32 truth: order 1e-2 relative, not garbage
        scale = np.abs(r32).max() + 1e-8
        assert np.abs(o - r32).max() / scale < 5e-2, mi


def test_bf16_backward_is_finite_and_close_on_tpu():
    """Gradients through the bf16 kernel drive the Adam phase on hardware
    — they must be finite and within bf16-class distance of the f32
    gradients (the L-BFGS phase always runs f32, collocation.py)."""
    layers, X = _setup()
    keys = sorted(REQS | {()})
    fn = pallas_taylor.build_pallas_table_fn(REQS, SHAPES, precision=PREC,
                                             compute_dtype=jnp.bfloat16)

    def loss_pl(ls):
        t = fn(ls, X)
        return sum(jnp.sum(t[k] ** 2) for k in keys)

    def loss_ref(ls):
        t = taylor_derivatives(ls, X, REQS | {()}, precision=PREC)
        return sum(jnp.sum(t[k] ** 2) for k in keys)

    g_pl = jax.grad(loss_pl)(layers)
    g_ref = jax.grad(loss_ref)(layers)
    for (gW, gb), (rW, rb) in zip(g_pl, g_ref):
        assert bool(jnp.all(jnp.isfinite(gW))) and \
            bool(jnp.all(jnp.isfinite(gb)))
        scale = float(jnp.max(jnp.abs(rW))) + 1e-8
        assert float(jnp.max(jnp.abs(gW - rW))) / scale < 5e-2
        # bias cotangents get the same closeness bar as the weights — a
        # wrong-but-finite bias gradient must fail, not pass (ADVICE r3)
        scale_b = float(jnp.max(jnp.abs(rb))) + 1e-8
        assert float(jnp.max(jnp.abs(gb - rb))) / scale_b < 5e-2


def test_third_order_and_mixed_on_tpu():
    """KdV-style u_xxx and mixed u_xt lower and match on hardware."""
    layers, X = _setup(n=500)
    reqs = {(0, 0, 0), (0, 1)}
    fn = pallas_taylor.build_pallas_table_fn(reqs, SHAPES, precision=PREC)
    out = fn(layers, X)
    ref = taylor_derivatives(layers, X, reqs | {()}, precision=PREC)
    for mi in out:
        np.testing.assert_allclose(np.asarray(out[mi]), np.asarray(ref[mi]),
                                   rtol=1e-5, atol=1e-6)
