"""On-hardware pallas parity: the Mosaic-compiled Taylor-table kernels must
match the XLA engine numerically, forward AND backward, on a real TPU.

Interpret-mode CI (``tests/test_pallas.py``) cannot catch hardware-only
failures — round 2 found three: ``scatter`` has no Mosaic lowering (the
one-hot derivative seeds), the batched ``[C, N, in] @ W`` weight-cotangent
transpose is a double contraction ``tpu.matmul`` rejects, and the backward
kernel's VJP residuals overflow the ~16 MB scoped-VMEM budget at the
forward tile size.  These tests pin all three fixes at the AC headline
shape (2-128x4-1, the reference ``examples/AC-SA.py`` network).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensordiffeq_tpu.ops import pallas_taylor
from tensordiffeq_tpu.ops.taylor import taylor_derivatives

pytestmark = pytest.mark.skipif(
    not pallas_taylor.available(),
    reason="real TPU backend required (pallas Mosaic path)")

PREC = jax.lax.Precision.HIGHEST
SHAPES = [(2, 128), (128, 128), (128, 128), (128, 128), (128, 1)]
REQS = {(1,), (0, 0)}  # u_t, u_xx — the Allen-Cahn request set


def _setup(n=2500, seed=0):
    rng = np.random.RandomState(seed)
    layers = [(jnp.asarray(rng.randn(i, o) / np.sqrt(i), jnp.float32),
               jnp.asarray(rng.randn(o) * 0.01, jnp.float32))
              for i, o in SHAPES]
    X = jnp.asarray(rng.rand(n, 2), jnp.float32)
    return layers, X


def test_forward_matches_xla_on_tpu():
    layers, X = _setup()
    fn = pallas_taylor.build_pallas_table_fn(REQS, SHAPES, precision=PREC)
    out = fn(layers, X)
    ref = taylor_derivatives(layers, X, REQS | {()}, precision=PREC)
    for mi in out:
        np.testing.assert_allclose(np.asarray(out[mi]), np.asarray(ref[mi]),
                                   rtol=1e-5, atol=1e-6)


def test_backward_matches_xla_on_tpu():
    layers, X = _setup()
    keys = sorted(REQS | {()})
    fn = pallas_taylor.build_pallas_table_fn(REQS, SHAPES, precision=PREC)

    def loss_pl(ls):
        t = fn(ls, X)
        return sum(jnp.sum(t[k] ** 2) for k in keys)

    def loss_ref(ls):
        t = taylor_derivatives(ls, X, REQS | {()}, precision=PREC)
        return sum(jnp.sum(t[k] ** 2) for k in keys)

    g_pl = jax.grad(loss_pl)(layers)
    g_ref = jax.grad(loss_ref)(layers)
    for (gW, gb), (rW, rb) in zip(g_pl, g_ref):
        scale = float(jnp.max(jnp.abs(rW))) + 1e-8
        assert float(jnp.max(jnp.abs(gW - rW))) / scale < 1e-5
        scale = float(jnp.max(jnp.abs(rb))) + 1e-8
        assert float(jnp.max(jnp.abs(gb - rb))) / scale < 1e-5


def test_point_cotangent_matches_on_tpu():
    """dX through the table (collocation-point adaptation path)."""
    layers, X = _setup(n=300)
    keys = sorted(REQS | {()})
    fn = pallas_taylor.build_pallas_table_fn(REQS, SHAPES, precision=PREC)

    def loss_pl(Xv):
        t = fn(layers, Xv)
        return sum(jnp.sum(t[k] ** 2) for k in keys)

    def loss_ref(Xv):
        t = taylor_derivatives(layers, Xv, REQS | {()}, precision=PREC)
        return sum(jnp.sum(t[k] ** 2) for k in keys)

    gX = jax.grad(loss_pl)(X)
    rX = jax.grad(loss_ref)(X)
    scale = float(jnp.max(jnp.abs(rX))) + 1e-8
    assert float(jnp.max(jnp.abs(gX - rX))) / scale < 1e-5


def test_third_order_and_mixed_on_tpu():
    """KdV-style u_xxx and mixed u_xt lower and match on hardware."""
    layers, X = _setup(n=500)
    reqs = {(0, 0, 0), (0, 1)}
    fn = pallas_taylor.build_pallas_table_fn(reqs, SHAPES, precision=PREC)
    out = fn(layers, X)
    ref = taylor_derivatives(layers, X, reqs | {()}, precision=PREC)
    for mi in out:
        np.testing.assert_allclose(np.asarray(out[mi]), np.asarray(ref[mi]),
                                   rtol=1e-5, atol=1e-6)
