"""Hardware test configuration — REAL backend, no CPU forcing.

Unlike ``tests/`` (which pins an 8-virtual-device CPU mesh so CI never
needs an accelerator), everything under ``hwtests/`` runs on whatever
backend JAX picks natively and skips itself when that backend is not a
TPU.  Run directly:

    python -m pytest hwtests/ -q

This is where on-hardware-only behaviour is guarded: Mosaic lowering of
the pallas kernels (scatter/batched-matmul restrictions that interpret
mode does not enforce), scoped-VMEM budgets, and MXU numerics.
"""
