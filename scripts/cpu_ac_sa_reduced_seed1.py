"""Seed-robustness pair for the reduced SA-vs-vanilla Allen-Cahn control.

The recorded pair (``runs/cpu_ac_sa_reduced.json``: SA 4.34e-2 vs vanilla
5.43e-1, a 12.5× gap reproducing the SA-PINN paper's headline claim) is a
single seed.  This runs the identical protocol at seed 1 — independent
net init, collocation draw, and λ init — so the flagship scientific claim
(per-point minimax rescues AC where vanilla fails) doesn't rest on one
lucky draw.  Arms are checkpoint-free but each arm's result is written
as soon as it finishes, so a session boundary costs one arm, not both.

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    nice -n 19 python scripts/cpu_ac_sa_reduced_seed1.py
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "examples"))
sys.path.insert(0, ROOT)

N_F, NX, NT = 10_000, 512, 201
WIDTHS = [64, 64, 64]
ADAM, NEWTON = 10_000, 10_000
SEED = 1
OUT = os.path.join(ROOT, "runs", "cpu_ac_sa_reduced_seed1.json")


def run(adaptive: bool):
    from ac_baseline import build_problem

    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import CollocationSolverND
    from tensordiffeq_tpu.exact import allen_cahn_solution

    domain, bcs, f_model = build_problem(N_F, nx=NX, nt=NT, seed=SEED)
    solver = CollocationSolverND(verbose=False, seed=SEED)
    kw = {}
    if adaptive:
        rng = np.random.RandomState(SEED)
        kw = dict(Adaptive_type=1,
                  dict_adaptive={"residual": [True], "BCs": [True, False]},
                  init_weights={"residual": [rng.rand(N_F, 1)],
                                "BCs": [100.0 * rng.rand(NX, 1), None]})
    solver.compile([2, *WIDTHS, 1], f_model, domain, bcs, **kw)
    t0 = time.time()
    solver.fit(tf_iter=ADAM, newton_iter=NEWTON)
    wall = time.time() - t0

    x, t, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = solver.predict(Xg, best_model=True)
    err = float(tdq.find_L2_error(u_pred, usol.reshape(-1, 1)))
    return {"adaptive": adaptive, "rel_l2": err, "wall_s": round(wall, 1),
            "seed": SEED,
            "config": f"N_f={N_F}, 2-{'x'.join(map(str, WIDTHS))}-1, "
                      f"{ADAM} Adam + {NEWTON} L-BFGS"}


def main():
    results = {}
    if os.path.exists(OUT):
        with open(OUT) as fh:
            results = json.load(fh).get("arms", {})
    for name, adaptive in (("sa", True), ("vanilla", False)):
        if name in results:
            print(f"[{name}] cached: rel-L2={results[name]['rel_l2']:.3e}",
                  flush=True)
            continue
        print(f"[{name}] running...", flush=True)
        results[name] = run(adaptive)
        payload = {"arms": results, "seed": SEED,
                   "note": "independent-seed repeat of "
                           "runs/cpu_ac_sa_reduced.json (seed 0: SA "
                           "4.34e-2 vs vanilla 5.43e-1)"}
        if "sa" in results and "vanilla" in results:
            payload["gap"] = round(results["vanilla"]["rel_l2"]
                                   / results["sa"]["rel_l2"], 2)
        with open(OUT + ".tmp", "w") as fh:
            json.dump(payload, fh, indent=1)
        os.replace(OUT + ".tmp", OUT)
        print(f"[{name}] rel-L2={results[name]['rel_l2']:.3e}", flush=True)
    print(json.dumps({k: v["rel_l2"] for k, v in results.items()}))


if __name__ == "__main__":
    main()
