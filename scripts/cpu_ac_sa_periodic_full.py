"""FULL-size AC-SA with the exactly-periodic embedding net, on CPU.

The reduced controlled comparison (``runs/cpu_ac_sa_periodic.json``)
measured the periodic ansatz worth 5.6× accuracy on Allen-Cahn (7.73e-3
vs 4.34e-2, identical seed/draw/budget) — already under the SA-PINN
paper's FULL-size bar (2.1e-2, cited at reference ``models.py:37``) at a
five-times-smaller config.  This run asks the full question: the
flagship config (N_f=50k, 2-128×4-1, λ_res U[0,1], λ_IC 100·U[0,1],
10k Adam + 10k L-BFGS — reference ``examples/AC-SA.py:12,55-56,64``)
with ``network=periodic_net(...)`` as the single change.

Streams a rel-L2 timeline every 250 epochs and checkpoints alongside, so
a session boundary yields a partial CONVERGENCE row + a resume point
instead of nothing (the full config is ~hours on one CPU core).

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    nice -n 15 python scripts/cpu_ac_sa_periodic_full.py
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "examples"))
sys.path.insert(0, ROOT)

N_F, NX, NT = 50_000, 512, 201
WIDTHS = [128, 128, 128, 128]
ADAM, NEWTON = 10_000, 10_000
EVAL_EVERY = 250
CKPT = os.path.join(ROOT, "runs", "ck_ac_sa_periodic_cpu_full")
META = os.path.join(ROOT, "runs", "cpu_ac_sa_periodic_full_meta.json")
OUT = os.path.join(ROOT, "runs", "cpu_ac_sa_periodic_full.json")


def main():
    from ac_baseline import build_problem

    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import CollocationSolverND
    from tensordiffeq_tpu.exact import allen_cahn_solution
    from tensordiffeq_tpu.helpers import find_L2_error

    domain, bcs, f_model = build_problem(N_F, nx=NX, nt=NT)
    rng = np.random.RandomState(0)
    solver = CollocationSolverND(verbose=False)
    solver.compile(
        [2, *WIDTHS, 1], f_model, domain, bcs, Adaptive_type=1,
        dict_adaptive={"residual": [True], "BCs": [True, False]},
        init_weights={"residual": [rng.rand(N_F, 1)],
                      "BCs": [100.0 * rng.rand(NX, 1), None]},
        network=tdq.periodic_net([2, *WIDTHS, 1], domain, ["x"]))

    meta = {"adam_done": 0, "newton_done": 0, "t_prev": 0.0,
            "timeline": [], "windows": 0}
    if os.path.exists(os.path.join(CKPT, "tdq_meta.json")):
        try:
            solver.restore_checkpoint(CKPT)
            if os.path.exists(META):
                with open(META) as fh:
                    meta = json.load(fh)
            nd = max(int(getattr(solver, "newton_done", 0)),
                     int(meta["newton_done"]))
            meta["newton_done"] = nd
            solver.newton_done = nd
            meta["adam_done"] = max(meta["adam_done"],
                                    min(len(solver.losses) - nd, ADAM))
            print(f"[pfull] resumed: {meta['adam_done']} Adam, "
                  f"{nd} L-BFGS, {meta['t_prev']:.0f}s", flush=True)
        except Exception as e:
            print(f"[pfull] ckpt not restorable ({e}); fresh", flush=True)
    meta["windows"] += 1
    t0 = time.time()

    x, t, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_star = usol.reshape(-1, 1)
    Xg_j = None

    def persist(status, l2=None):
        tnow = round(meta["t_prev"] + time.time() - t0, 1)
        with open(META + ".tmp", "w") as fh:
            json.dump(dict(meta, t_prev=tnow), fh)
        os.replace(META + ".tmp", META)
        out = {"arm": "periodic_net SA (FULL flagship config)",
               "config": f"N_f={N_F}, 2-128x4-1, {ADAM}+{NEWTON}, seed 0, "
                         "periodic_net(n_harmonics=4); reference "
                         "examples/AC-SA.py:12,55-56,64 + exact-periodic "
                         "ansatz", "backend": "cpu-1core",
               "status": status, "rel_l2": l2, "wall_s": tnow,
               "adam_done": meta["adam_done"],
               "newton_done": meta["newton_done"],
               "timeline": meta["timeline"]}
        with open(OUT + ".tmp", "w") as fh:
            json.dump(out, fh, indent=1)
        os.replace(OUT + ".tmp", OUT)

    def eval_fn(phase, step, params):
        nonlocal Xg_j
        import jax.numpy as jnp
        if Xg_j is None:
            Xg_j = jnp.asarray(Xg, jnp.float32)
        l2 = float(find_L2_error(np.asarray(solver._apply_jit(params, Xg_j)),
                                 u_star))
        abs_step = step + (meta["adam_done"] if phase == "adam"
                           else meta["newton_done"])
        tnow = round(meta["t_prev"] + time.time() - t0, 1)
        meta["timeline"].append(
            {"t": tnow, "phase": f"{phase}@{abs_step}", "l2": l2})
        print(f"[pfull] t={tnow:8.1f}s {phase}@{abs_step}: "
              f"rel-L2={l2:.3e}", flush=True)
        persist("partial", l2)

    solver.fit(tf_iter=ADAM - meta["adam_done"],
               newton_iter=NEWTON - meta["newton_done"],
               eval_fn=eval_fn, eval_every=EVAL_EVERY,
               checkpoint_dir=CKPT, checkpoint_every=EVAL_EVERY)

    u_pred, _ = solver.predict(Xg, best_model=True)
    err = float(find_L2_error(u_pred, u_star))
    meta["adam_done"], meta["newton_done"] = ADAM, NEWTON
    persist("complete", err)
    print(json.dumps({"arm": "periodic_net SA full", "rel_l2": err}),
          flush=True)
    import shutil
    for d in (CKPT, CKPT + ".old", CKPT + ".tmp"):
        shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    main()
