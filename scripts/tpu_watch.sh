#!/bin/bash
# Probe the TPU tunnel; when it answers, run the full evidence queue once.
# Detached-safe: writes state to runs/tpu_watch.state so a supervisor (or a
# human) can see where it is.  Probe subprocesses are killed on timeout so a
# hung dial never wedges the watcher or holds the axon lock.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
STATE=runs/tpu_watch.state

# Singleton guard: two watchers racing the evidence suite on this 1-core
# host would double every run and race the promote step (round-3 cleanup:
# two instances were found running).  flock on fd 9 held for process life.
exec 9>runs/tpu_watch.lock
if ! flock -n 9; then
    echo "another tpu_watch.sh holds runs/tpu_watch.lock; exiting" >&2
    exit 0
fi

HIST=runs/tunnel_history.log   # append-only probe record (audit + trend)

while true; do
    echo "probing $(date +%H:%M:%S)" > "$STATE"
    if timeout 120 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform != 'cpu'
(jnp.ones((128,128)) @ jnp.ones((128,128))).block_until_ready()
print('healthy')
" 9<&- 2>/dev/null | grep -q healthy; then
        echo "$(date -u +%F\ %T) healthy" >> "$HIST"
        echo "healthy $(date +%H:%M:%S) — running evidence suite" > "$STATE"
        bash scripts/tpu_evidence.sh 9<&- >> runs/tpu_evidence_watch.log 2>&1
        bash scripts/tpu_convergence_extra.sh 9<&- >> runs/tpu_extra_watch.log 2>&1
        # a mid-suite tunnel death leaves gaps — keep watching until the
        # core artifacts exist AND are complete (have_complete: a promoted
        # gap-filler partial must keep the watcher alive for the re-run)
        . scripts/_promote.sh
        if have_complete full && have_complete default \
            && have_complete precision && have_complete engines \
            && have_complete scale && have_complete remat \
            && grep -qE '"status": "(complete|exhausted)"' BENCH_TPU_northstar.json 2>/dev/null \
            && grep -q "passed" runs/hwtests_tpu.log 2>/dev/null \
            && grep -aq "Error u" runs/ac_baseline_full_tpu.log 2>/dev/null \
            && grep -aq "Error u" runs/burgers_full_tpu.log 2>/dev/null \
            && grep -aq "c1 = " runs/ac_discovery_full_nosa12k_tpu.log 2>/dev/null \
            && grep -aq "c1 = " runs/ac_discovery_sa10k_tpu.log 2>/dev/null \
            && grep -aq "relative L2" runs/kdv_full_tpu.log 2>/dev/null \
            && grep -aq "final loss" runs/burgers2d_full_tpu.log 2>/dev/null \
            && grep -qE '"status": "(complete|exhausted)"' BENCH_TPU_northstar_periodic.json 2>/dev/null \
            && grep -aq "Error u" runs/schrodinger_full_tpu.log 2>/dev/null \
            && grep -aq "improvement" runs/resample_ablation_tpu.log 2>/dev/null; then
            echo "done $(date +%H:%M:%S)" > "$STATE"
            exit 0
        fi
        echo "suite incomplete $(date +%H:%M:%S); will re-pass" > "$STATE"
    else
        echo "$(date -u +%F\ %T) unhealthy" >> "$HIST"
        echo "unhealthy $(date +%H:%M:%S); retrying in 300s" > "$STATE"
    fi
    # 9<&- : children must NOT inherit the lock fd — a sleep/evidence child
    # outliving a killed watcher would block every relaunch for minutes
    # (round-4 incident: an orphaned `sleep 300` held the lock)
    sleep 300 9<&-
done
