#!/bin/bash
# Probe the TPU tunnel; when it answers, run the full evidence queue once.
# Detached-safe: writes state to runs/tpu_watch.state so a supervisor (or a
# human) can see where it is.  Probe subprocesses are killed on timeout so a
# hung dial never wedges the watcher or holds the axon lock.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
STATE=runs/tpu_watch.state

while true; do
    echo "probing $(date +%H:%M:%S)" > "$STATE"
    if timeout 120 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform != 'cpu'
(jnp.ones((128,128)) @ jnp.ones((128,128))).block_until_ready()
print('healthy')
" 2>/dev/null | grep -q healthy; then
        echo "healthy $(date +%H:%M:%S) — running evidence suite" > "$STATE"
        bash scripts/tpu_evidence.sh > runs/tpu_evidence_watch.log 2>&1
        bash scripts/tpu_convergence_extra.sh > runs/tpu_extra_watch.log 2>&1
        echo "done $(date +%H:%M:%S)" > "$STATE"
        exit 0
    fi
    echo "unhealthy $(date +%H:%M:%S); retrying in 300s" > "$STATE"
    sleep 300
done
