#!/usr/bin/env python
"""Render CONVERGENCE.md's zoo-scorecard section from a scorecard JSON.

The prose history above the markers is hand-written and stays; the table
between ``<!-- zoo-scorecard:begin -->`` / ``<!-- zoo-scorecard:end -->``
is GENERATED from the machine-readable scorecard (``SCORECARD.json``, or
any ``bench.py --zoo`` payload passed as argv[1]) so the results table
can never drift from what the harness measured.

    python scripts/convergence_table.py            # splice SCORECARD.json
    python scripts/convergence_table.py card.json  # splice another card
    python scripts/convergence_table.py --stdout   # print, don't write
"""

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
BEGIN = "<!-- zoo-scorecard:begin -->"
END = "<!-- zoo-scorecard:end -->"


def _fmt_metric(v):
    return "—" if v is None else f"{v:.3g}"


def _arm_cell(arm, gate_kind):
    if arm is None:
        return "—"
    metric = arm.get("rel_l2_final" if gate_kind == "rel_l2"
                     else "residual_final")
    if arm.get("gated"):
        return (f"**✓** @ {arm['steps_to_gate']} steps "
                f"({_fmt_metric(metric)})")
    return f"✗ {_fmt_metric(metric)}"


def render(doc) -> str:
    from tensordiffeq_tpu.zoo import scorecard_of

    card = scorecard_of(doc)
    backend = doc.get("backend", "cpu")
    lines = [
        BEGIN,
        "",
        "## Zoo scorecard (generated — do not hand-edit this section)",
        "",
        f"Measured by `bench.py --zoo` at the registry's declared "
        f"`{card['size']}` budgets on `{backend}`; regenerate with "
        "`python scripts/convergence_table.py`.  Per entry, the three "
        "adaptive-collocation arms race to the entry's declared gate "
        "(rel-L2 against the reference, or held-out RMS residual for "
        "residual-only entries); ✓ cells show the cumulative optimizer "
        "step from which the gate was reached AND HELD through the end "
        "of the budget (transient dips don't gate), and every cell "
        "carries the final metric.  The CI diff gate "
        "(`bench.py --zoo-diff`) holds "
        "future runs to the ✓ cells recorded here.",
        "",
        "| Entry | Engine | Budget (Adam+L-BFGS) | Gate | fixed | "
        "pool | ascent |",
        "|---|---|---|---|---|---|---|",
    ]
    for eid, e in sorted(card["entries"].items()):
        gate = e["gate"]
        gate_cell = (f"rel-L2 ≤ {gate['value']:g}"
                     if gate["kind"] == "rel_l2"
                     else f"RMS residual ≤ {gate['value']:g}")
        name = f"**{eid}**" if e.get("system") else eid
        if e.get("system"):
            name += f" ({e['n_components']}-comp system)"
        lines.append(
            f"| {name} | `{e['engine']}` "
            f"| {e['budget']['adam']}+{e['budget']['lbfgs']} "
            f"| {gate_cell} "
            f"| {_arm_cell(e['arms'].get('fixed'), gate['kind'])} "
            f"| {_arm_cell(e['arms'].get('pool'), gate['kind'])} "
            f"| {_arm_cell(e['arms'].get('ascent'), gate['kind'])} |")
    lines += ["", END]
    return "\n".join(lines)


def splice(text: str, section: str) -> str:
    if BEGIN in text and END in text:
        head, rest = text.split(BEGIN, 1)
        _, tail = rest.split(END, 1)
        return head + section + tail
    return text.rstrip("\n") + "\n\n" + section + "\n"


def main(argv):
    to_stdout = "--stdout" in argv
    argv = [a for a in argv if a != "--stdout"]
    card_path = argv[0] if argv else os.path.join(ROOT, "SCORECARD.json")
    with open(card_path) as fh:
        doc = json.load(fh)
    section = render(doc)
    if to_stdout:
        print(section)
        return
    conv = os.path.join(ROOT, "CONVERGENCE.md")
    with open(conv) as fh:
        text = fh.read()
    with open(conv, "w") as fh:
        fh.write(splice(text, section))
    print(f"spliced zoo scorecard ({len(doc.get('scorecard', doc).get('entries', {}))} "
          f"entries) from {os.path.relpath(card_path, ROOT)} into "
          f"CONVERGENCE.md")


if __name__ == "__main__":
    main(sys.argv[1:])
