"""CPU hedge: FULL-size Allen-Cahn SA-PINN — the flagship config.

The reference's headline example (``/root/reference/examples/AC-SA.py:12,
55-56,64``): N_f=50,000 collocation points, 2-128x4-1 tanh MLP, per-point
lambda_res ~ U[0,1], lambda_IC ~ 100*U[0,1], 10k Adam + 10k L-BFGS.  This
config has never run to convergence on ANY backend here (VERDICT r4,
Missing #4) — the TPU queue has it as step 1, but the tunnel decides when
that happens.  This script is the tunnel-independent path: it drives the
SAME machinery the TPU run uses (``bench.bench_time_to_l2`` — crash-safe
mid-run checkpoints every eval, cumulative productive-time timeline,
resume-on-restart) on the one CPU core, nice'd so interactive work wins.

At CPU rates a straight 10k+10k run spans multiple sessions; each
invocation extends the same checkpoint (``runs/ac_sa_full_cpu_ckpt`` —
deliberately NOT the TPU queue's ``runs/full_ckpt``, so CPU productive
time never contaminates an on-chip timeline) and streams the partial
rel-L2 timeline to ``runs/ac_sa_full_cpu.json`` after every eval.

Usage (see scripts/cpu_evidence_r5.sh):
  env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      nice -n 19 python scripts/cpu_ac_sa_full.py
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Own checkpoint namespace; never collide with the TPU queue's run.
os.environ.setdefault("BENCH_FULL_CKPT",
                      os.path.join(REPO, "runs", "ac_sa_full_cpu_ckpt"))

N_F, NX, NT = 50_000, 512, 201
WIDTHS = [128, 128, 128, 128]
ADAM, NEWTON = 10_000, 10_000
EVAL_EVERY = 50  # ~20 min of epochs per checkpoint at 1-core rates

OUT = os.path.join(REPO, "runs", "ac_sa_full_cpu.json")


def main():
    import bench

    def on_eval(snap):
        payload = {
            "run": "AC-SA full (flagship config, CPU hedge)",
            "config": f"N_f={N_F}, 2-128x4-1, {ADAM}+{NEWTON}, "
                      "lam_res U[0,1], lam_IC 100*U[0,1] "
                      "(reference examples/AC-SA.py:12,55-56,64)",
            "backend": "cpu-1core",
            "status": "partial",
            **snap,
        }
        with open(OUT + ".tmp", "w") as fh:
            json.dump(payload, fh, indent=1)
        os.replace(OUT + ".tmp", OUT)

    res = bench.bench_time_to_l2(
        N_F, NX, NT, WIDTHS,
        adam_iter=ADAM, newton_iter=NEWTON,
        eval_every=EVAL_EVERY, on_eval=on_eval,
        # autotune costs ~4x the compiles and its CPU pick for the AC-SA
        # step config is the generic engine (BENCH_TPU_engines autotune
        # history); pin it so the first checkpoint lands sooner
        fused="generic")
    res.update(run="AC-SA full (flagship config, CPU hedge)",
               backend="cpu-1core", status="complete")
    with open(OUT + ".tmp", "w") as fh:
        json.dump(res, fh, indent=1)
    os.replace(OUT + ".tmp", OUT)
    print(json.dumps({k: v for k, v in res.items() if k != "timeline"}))


if __name__ == "__main__":
    main()
