#!/usr/bin/env bash
# tdqlint local entry point: the same AST pass tier-1 gates on
# (tests/test_lint_clean.py) and bench.py --lint wires into CI.
#
#   scripts/lint.sh                # AST rules over the package + bench.py
#   scripts/lint.sh --jaxpr        # + the jaxpr-level hot-program audit
#   scripts/lint.sh --list-rules   # rule ids + one-line docs
#
# Exit codes: 0 clean, 1 findings, 2 usage error.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m tensordiffeq_tpu.analysis "$@"
