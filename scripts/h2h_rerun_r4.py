"""Round-4 time-to-bar rerun of the head-to-head, on a clean host.

Round 3's full head-to-head (runs/head_to_head.json) gave the reference
time-to-rel-L2<=5e-2 = 486 s vs our 688 s, with our end-to-end 1.58x
faster.  Two deficits were diagnosed: ~100 s of XLA compile inside our
clock (now removed by the persistent compile cache) and a per-iter Adam
rate (~2.3 it/s) far below what this host measures clean (~8-16 it/s) —
the round-3 run shared its single CPU core with other evidence jobs.

This rerun measures ONLY the race to the bar (3k Adam, no Newton: both
frameworks crossed the bar in Adam round 3) with the host otherwise
idle, both arms back-to-back under identical conditions:

  1. reference arm  — unmodified TF reference via run_reference()
  2. ours, cold     — fresh compile-cache dir (pays XLA compiles)
  3. ours, warm     — same dir (compiles load from disk)

Our arm runs the generic jvp residual engine (H2H_FUSED=generic): the
fused Taylor engine's batched-matmul layout is an MXU design, and on
CPU at this narrow 2-20x8-1 net the generic engine measures ~2x faster
— exactly what compile(fused="autotune") would pick.  Eval every 250
iters (denser than the reference's 1000-iter grid; in our clock).

Arms run as separate processes so cold/warm is a real process boundary.
Writes runs/h2h_r4.json; never touches the round-3 artifact.

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
           python scripts/h2h_rerun_r4.py [--adam 3000]
"""
import argparse
import json
import os
import shutil
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "runs", "h2h_r4.json")
CACHE = os.path.join(ROOT, "runs", "h2h_r4_cache")


def run_arm(which, adam, env_extra):
    """One arm in a subprocess; returns the parsed result dict."""
    code = (
        "import json, sys; sys.path.insert(0, 'scripts'); "
        "from head_to_head import run_reference, run_ours; "
        f"r = {'run_reference' if which == 'tf' else 'run_ours'}({adam}, 0); "
        "print('H2H_RESULT ' + json.dumps(r))"
    )
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               **env_extra)
    p = subprocess.run([sys.executable, "-c", code], cwd=ROOT, env=env,
                       capture_output=True, text=True, timeout=7200)
    for line in (p.stdout or "").splitlines():
        if line.startswith("H2H_RESULT "):
            return json.loads(line[len("H2H_RESULT "):])
    raise RuntimeError(f"arm {which} produced no result "
                       f"(rc={p.returncode}):\n{p.stderr[-2000:]}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--adam", type=int, default=3000)
    args = ap.parse_args()

    results = {}
    if os.path.exists(OUT):
        with open(OUT) as fh:
            results = json.load(fh)

    def save():
        with open(OUT, "w") as fh:
            json.dump(results, fh, indent=1)

    results["config"] = {"n_f": 10_000, "net": "2-20x8-1",
                         "adam": args.adam, "newton": 0, "bar": 5e-2,
                         "host": "1 CPU core, idle",
                         "ours_engine": "generic (autotune's CPU pick)",
                         "eval_every_ours": 250}

    ours_env = {"H2H_FUSED": "generic", "H2H_EVAL_EVERY": "250",
                "TDQ_COMPILE_CACHE": CACHE}
    for key, which, env in (
            ("reference-tf", "tf", {}),
            ("ours-cold", "jax", ours_env),
            ("ours-warm", "jax", ours_env)):
        if key in results:
            print(f"[{key}] cached: time_to_bar="
                  f"{results[key].get('time_to_bar')}", flush=True)
            continue
        if key == "ours-cold" and os.path.isdir(CACHE):
            shutil.rmtree(CACHE)  # cold must really be cold
        print(f"[{key}] running ({args.adam} Adam)...", flush=True)
        results[key] = run_arm(which, args.adam, env)
        print(f"[{key}] time_to_bar={results[key].get('time_to_bar')} "
              f"wall={results[key].get('wall')}", flush=True)
        save()

    ref_bar = results["reference-tf"].get("time_to_bar")
    for key in ("ours-cold", "ours-warm"):
        bar = results[key].get("time_to_bar")
        if ref_bar and bar:
            results[f"speedup_{key.split('-')[1]}"] = round(ref_bar / bar, 2)
    save()
    print(json.dumps({k: (v.get("time_to_bar") if isinstance(v, dict)
                          and "time_to_bar" in v else v)
                      for k, v in results.items() if k != "config"}),
          flush=True)


if __name__ == "__main__":
    main()
