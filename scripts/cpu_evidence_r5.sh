#!/bin/bash
# Round-5 CPU evidence queue (sequential; each step idempotent via its
# own runs/*.json guards).  Runs AFTER the annealed-causal ablation arm
# that launched at round start; the AC-SA full hedge runs in parallel at
# nice 19 the whole session.
set -u
cd "$(dirname "$0")/.."
export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu
# step 1: annealed-causal arm (skips arms already recorded)
ABLATION_EXTRA=causal_anneal nice -n 15 python scripts/cpu_weighting_ablation.py \
  >> runs/weighting_anneal.log 2>&1
# step 2: NTK trace-subsample sensitivity (256/512/1024)
nice -n 15 python scripts/cpu_ntk_helmholtz.py --sens \
  >> runs/ntk_sensitivity.log 2>&1
echo "r5 cpu evidence queue done $(date -u)" >> runs/cpu_evidence_r5.log
