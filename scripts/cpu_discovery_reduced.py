"""Reduced Allen-Cahn coefficient discovery on CPU (evidence hedge).

Full config (512x201 grid, 4x128, 10k Adam — reference AC-discovery.py) is
TPU-queue step C; this reduced run ([::4] subsampled 128x51 grid, 4x64 net,
SA col_weights, 6000 Adam) demonstrates honest coefficient recovery for the
inverse solver on one CPU core.  True values: c1 = 0.0001, c2 = 5.0.

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/cpu_discovery_reduced.py
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

from tensordiffeq_tpu import DiscoveryModel, grad
from tensordiffeq_tpu.exact import allen_cahn_solution


def main():
    x, t, usol = allen_cahn_solution()
    x, t, usol = x[::4], t[::4], usol[::4, ::4]
    X = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_star = usol.reshape(-1, 1)

    def f_model(u, var, x, t):
        c1, c2 = var
        u_xx = grad(grad(u, "x"), "x")
        uv = u(x, t)
        return grad(u, "t")(x, t) - c1 * u_xx(x, t) + c2 * uv ** 3 - c2 * uv

    rng = np.random.RandomState(0)
    model = DiscoveryModel()
    model.compile([2, 64, 64, 64, 64, 1], f_model,
                  [X[:, 0:1], X[:, 1:2]], u_star, var=[0.0, 0.0],
                  col_weights=rng.rand(X.shape[0], 1), varnames=["x", "t"])
    t0 = time.time()
    model.fit(tf_iter=6_000)
    wall = time.time() - t0

    c1, c2 = (float(v) for v in model.vars)
    out = {"grid": f"{len(x)}x{len(t)}", "net": "2-64x4-1", "adam": 6_000,
           "c1": c1, "c1_true": 0.0001, "c2": c2, "c2_true": 5.0,
           "c2_rel_err": abs(c2 - 5.0) / 5.0, "wall_s": round(wall, 1)}
    print(json.dumps(out), flush=True)
    with open(os.path.join(ROOT, "runs", "cpu_discovery_reduced.json"),
              "w") as fh:
        json.dump(out, fh, indent=1)


if __name__ == "__main__":
    main()
