"""Allen-Cahn coefficient discovery run to CONVERGENCE (CPU evidence).

Round-2's reduced run (6k iters, lr_vars=0.005) honestly reported
non-convergence: c2 was still climbing at cutoff (4.35 of 5.0).  This run
closes the gap on the full-x 512-point grid with the budget and PER-VAR
coefficient learning rates the problem actually needs (``lr_vars=
[2e-5, 0.01]`` — a public knob of ``DiscoveryModel.compile``; the
network keeps the reference's 0.005/b1=0.99).  True values: c1 = 0.0001
(diffusion), c2 = 5.0 (reaction) — reference ``examples/AC-discovery.py:
14,51-66`` recovers these on the full grid with a multi-GPU budget.

Crash-safe: checkpoints every 5k iters and resumes from the newest one,
so a killed host loses at most one leg.  The full coefficient trajectory
(every 10th iter) lands in runs/cpu_discovery_converge.json.

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
           python scripts/cpu_discovery_converge.py
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

from tensordiffeq_tpu import DiscoveryModel, grad
from tensordiffeq_tpu.exact import allen_cahn_solution

TOTAL = int(os.environ.get("DISC_ITERS", 12_000))
# DISC_SA=0 drops the SA col_weights: the 2026-07-31 per-var-lr run showed
# the unbounded λ ascent degrading the u-fit over long runs (loss 2.3e-4 at
# leg 2 -> 7.3e-3 at leg 4) and dragging c2 down with it (4.91 -> 4.32),
# while c1 converged to 9.4e-5 under its own rate.  Plain MSE keeps the
# fit stable; c1 no longer needs λ's interface emphasis.
SA = os.environ.get("DISC_SA", "1") != "0"
# DISC_G=tanh2 bounds the SA residual weight via g(λ)=tanh(λ)² (the
# compile(g=...) knob added after the λ-runaway diagnosis): λ may ascend
# without bound, but its LOSS weight cannot exceed 1 — testing whether
# this keeps the u-fit stable where the default λ² run drained c2.
G_NAME = os.environ.get("DISC_G", "")
# DISC_TSUB: time-axis subsample stride (8 -> t[::8] = 26 slices, the
# round-3 CPU-feasible grid; 1 -> the reference's FULL 512x201 grid).
# DISC_BATCH: observation minibatch size (0 = full batch).  The full grid
# is ~103k rows — full-batch is ~8x the 512x26 step cost and days on one
# CPU core, but minibatched at DISC_BATCH~12864 each step costs the same
# as the 512x26 full-batch step while the optimizer sees every row each
# 8-step sweep (DiscoveryModel.fit(batch_sz=...), round-4 capability).
TSUB = int(os.environ.get("DISC_TSUB", 8))
BATCH = int(os.environ.get("DISC_BATCH", 0))
SEED = int(os.environ.get("DISC_SEED", 0))  # network-init seed (robustness)
LEG = 3_000
# keep every variant's artifacts apart
_SUF = ("" if SA else "_nosa") + (f"_{G_NAME}" if G_NAME else "") \
    + (f"_t{TSUB}" if TSUB != 8 else "") + (f"_b{BATCH}" if BATCH else "") \
    + (f"_s{SEED}" if SEED else "")
# the ckpt dir additionally carries a config token (full-x grid + per-var
# lr labels): a leftover checkpoint from an older grid/optimizer layout
# must never be restored into this one (ADVICE r3) — and restore is
# belt-and-braces guarded below so an incompatible dir starts fresh
CKPT = os.path.join(ROOT, "runs", f"discovery_converge_ckpt{_SUF}_fx512pv")
OUT = os.path.join(ROOT, "runs", f"cpu_discovery_converge{_SUF}.json")


def main():
    x, t, usol = allen_cahn_solution()
    # FULL x-resolution, subsampled time: the first attempt subsampled BOTH
    # axes [::4] and converged to a biased solution (c2 peak 4.73 then
    # drift, c1 inflating steadily — runs/cpu_discovery_128x51_biased.json):
    # dx=0.0157 cannot resolve the AC interface width ~sqrt(c1_true)=0.01,
    # so the smoothed interfaces demand a larger effective diffusion.  The
    # 512-point x-grid (dx=0.0039, the reference's resolution) keeps the
    # interfaces; t[::8] (26 slices) is benign — AC dynamics are smooth in
    # t — and keeps the row count CPU-feasible.
    x, t, usol = x, t[::TSUB], usol[:, ::TSUB]
    X = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_star = usol.reshape(-1, 1)

    def f_model(u, var, x, t):
        c1, c2 = var
        u_xx = grad(grad(u, "x"), "x")
        uv = u(x, t)
        return grad(u, "t")(x, t) - c1 * u_xx(x, t) + c2 * uv ** 3 - c2 * uv

    rng = np.random.RandomState(0)
    model = DiscoveryModel()
    # per-var rates (round 3): lr_vars=0.01 shared was measured live to
    # park c1 at an Adam noise floor 10-20x its 1e-4 target while c2
    # climbed (c1=1.8e-3 at iter 6000, runs/ archive) — Adam normalizes
    # gradient magnitude, not curvature, and |∂f/∂c1|=|u_xx| is ~1e4
    # larger than |∂f/∂c2|.  Rate each coefficient at its own scale.
    g = None
    if G_NAME == "tanh2":
        import jax.numpy as jnp
        g = lambda lam: jnp.tanh(lam) ** 2  # noqa: E731
    elif G_NAME:
        raise ValueError(f"unknown DISC_G={G_NAME!r} (supported: tanh2)")
    model.compile([2, 64, 64, 64, 64, 1], f_model,
                  [X[:, 0:1], X[:, 1:2]], u_star, var=[0.0, 0.0],
                  col_weights=rng.rand(X.shape[0], 1) if SA else None,
                  varnames=["x", "t"], g=g, seed=SEED,
                  lr_vars=[2e-5, 0.01], verbose=False)

    done = 0
    if os.path.isdir(CKPT):
        try:
            model.restore_checkpoint(CKPT)
            done = len(model.var_history)
            print(f"[discovery] resumed at iter {done}", flush=True)
        except Exception as e:
            print(f"[discovery] checkpoint in {CKPT} incompatible with this "
                  f"config ({type(e).__name__}: {e}); starting fresh",
                  flush=True)

    t0 = time.time()
    while done < TOTAL:
        n = min(LEG, TOTAL - done)
        model.fit(tf_iter=n, batch_sz=BATCH or None)
        done += n
        model.save_checkpoint(CKPT)
        c1, c2 = (float(v) for v in model.vars)
        print(f"[discovery] iter {done}: c1={c1:.6f} c2={c2:.4f} "
              f"loss={model.losses[-1]:.3e} "
              f"({time.time() - t0:.0f}s)", flush=True)

    c1, c2 = (float(v) for v in model.vars)
    traj = model.var_history[::10]
    out = {"grid": f"{len(x)}x{len(t)}", "net": "2-64x4-1", "sa": SA, "g": G_NAME or "lambda^2 (default)",
           "adam": done, "lr_vars": "2e-5,0.01 (per-var)",
           "c1": c1, "c1_true": 0.0001, "c1_abs_err": abs(c1 - 0.0001),
           "c2": c2, "c2_true": 5.0,
           "c2_rel_err": abs(c2 - 5.0) / 5.0,
           "final_loss": float(model.losses[-1]),
           "wall_s_this_session": round(time.time() - t0, 1),
           "trajectory_every10": traj}
    with open(OUT, "w") as fh:
        json.dump(out, fh)
    print(json.dumps({k: v for k, v in out.items()
                      if k != "trajectory_every10"}), flush=True)


if __name__ == "__main__":
    main()
