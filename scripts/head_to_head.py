"""Time-to-accuracy head-to-head: the ACTUAL TF reference vs this framework.

Same host, same config — the reference's own Burgers headline
(``/root/reference/examples/burgers-new.py:12,35,40-41``: N_f=10k,
2-20x8-1 tanh MLP, 10k Adam + 10k L-BFGS), same ground truth (the
reference's ``burgers_shock.mat`` on the 256x100 grid its example
evaluates on, ``burgers-new.py:48-68``), same accuracy bar (rel-L2
<= 5e-2, the quality the reference's README cites for this example).
Reports wall-clock to the bar for each framework and the ratio — the
number a migrating user actually cares about, instead of step-rate
ratios (VERDICT r2 weak-4).

The reference runs UNMODIFIED from /root/reference via PYTHONPATH, with
one harness shim: ``tensorflow_probability`` is absent from this image
and the reference imports it at module scope (``optimizers.py:5``)
even though its default L-BFGS path is the eager one that never uses
it — a no-op stub module is injected so the import succeeds.

Fairness accounting (every correction here favors the REFERENCE, so the
reported speedup is a lower bound):

* The reference's Adam is driven in 1000-iter chunks through its own
  public ``fit`` so rel-L2 can be sampled (optimizer state lives on the
  model object and persists) — but each ``fit`` call re-wraps the grad
  step in a fresh ``tf.function`` (reference ``fit.py:35``), a re-trace
  an unchunked run pays once.  The harness measures that marginal
  per-call cost with two 1-iter warm-up fits and CREDITS it back: every
  reference timeline point is reported at
  ``t_raw - (fit_calls_so_far - 1) * retrace``.
* The reference's eager L-BFGS owns its loop, so rel-L2 is only
  observable at the end.  If the bar is first crossed by that final
  evaluation, the reference's ``time_to_bar`` is recorded as the
  L-BFGS phase START time — i.e. the reference is assumed to have
  crossed the bar the moment the phase began.
* Our run evaluates every 500 iters of BOTH phases (denser eval than
  the reference pays), included in our clock.

Usage:  python scripts/head_to_head.py [--adam N] [--newton N] [--which both|tf|jax]
Writes runs/head_to_head.json (merging, so tf/jax can run separately).
"""

import argparse
import json
import os
import sys
import time
import types

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "runs", "head_to_head.json")
BAR = 5e-2
ADAM_CHUNK = 1000
EVAL_EVERY_OURS = 500


def ground_truth():
    """The reference's own evaluation target: burgers_shock.mat on the
    256x100 meshgrid of the domain linspaces (burgers-new.py:48-68)."""
    import scipy.io
    data = scipy.io.loadmat("/root/reference/examples/burgers_shock.mat")
    u_star = np.real(data["usol"]).T.flatten()[:, None]  # [100*256, 1]
    x = np.linspace(-1.0, 1.0, 256)
    t = np.linspace(0.0, 1.0, 100)
    X, T = np.meshgrid(x, t)
    X_star = np.hstack([X.flatten()[:, None], T.flatten()[:, None]])
    return X_star.astype(np.float32), u_star.astype(np.float32)


def rel_l2(u_pred, u_star):
    return float(np.linalg.norm(u_pred - u_star) / np.linalg.norm(u_star))


def record(timeline, t, l2, phase):
    timeline.append({"t": round(t, 1), "l2": l2, "phase": phase})
    print(f"[h2h] t={t:8.1f}s {phase}: rel-L2={l2:.3e}", flush=True)


def time_to_bar(timeline):
    for p in timeline:
        if p["l2"] <= BAR:
            return p["t"]
    return None


# --------------------------------------------------------------------- #
def run_reference(adam_iter, newton_iter):
    # tfp stub: module-scope import only; the eager L-BFGS default never
    # touches it (fit.py newton_eager=True path)
    if "tensorflow_probability" not in sys.modules:
        sys.modules["tensorflow_probability"] = types.SimpleNamespace(
            optimizer=types.SimpleNamespace(lbfgs_minimize=None))
    if "pyDOE2" not in sys.modules:
        # the reference's LHS draw (sampling.py:9) — same Latin-Hypercube
        # semantics served by scipy.qmc; criterion optimization ignored
        # (layout detail, not a speed factor for either framework)
        from scipy.stats import qmc

        def lhs(n, samples=None, criterion=None, random_state=None, **_):
            return qmc.LatinHypercube(
                d=n, seed=random_state).random(samples or n)

        sys.modules["pyDOE2"] = types.SimpleNamespace(lhs=lhs)
    if "pyfiglet" not in sys.modules:
        # console-banner eye candy only (reference output.py:1)
        class _Figlet:
            def __init__(self, **_):
                pass

            def renderText(self, text):
                return text + "\n"

        sys.modules["pyfiglet"] = types.SimpleNamespace(Figlet=_Figlet)

    # keras-3 compat: the reference passes the keras-2 `lr=` alias
    # (models.py:49) which keras 3 rejects; translate, change nothing else
    import tensorflow as _tf
    _Adam = _tf.keras.optimizers.Adam
    if not getattr(_Adam, "_h2h_lr_compat", False):
        class _AdamCompat(_Adam):
            _h2h_lr_compat = True

            def __init__(self, *a, lr=None, **kw):
                if lr is not None:
                    kw.setdefault("learning_rate", lr)
                super().__init__(*a, **kw)

        _tf.keras.optimizers.Adam = _AdamCompat
    sys.path.insert(0, "/root/reference")
    import math

    import tensorflow as tf
    from tensordiffeq.boundaries import IC, DomainND, dirichletBC
    from tensordiffeq.models import CollocationSolverND

    X_star, u_star = ground_truth()

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], 256)
    domain.add("t", [0.0, 1.0], 100)
    domain.generate_collocation_points(10_000)

    def func_ic(x):
        return -np.sin(x * math.pi)

    bcs = [IC(domain, [func_ic], var=[["x"]]),
           dirichletBC(domain, val=0.0, var="x", target="upper"),
           dirichletBC(domain, val=0.0, var="x", target="lower")]

    def f_model(u_model, x, t):
        u = u_model(tf.concat([x, t], 1))
        u_x = tf.gradients(u, x)
        u_xx = tf.gradients(u_x, x)
        u_t = tf.gradients(u, t)
        return u_t + u * u_x - (0.01 / tf.constant(math.pi)) * u_xx

    model = CollocationSolverND()
    model.compile([2] + [20] * 8 + [1], f_model, domain, bcs)

    timeline = []
    t0 = time.time()
    # marginal cost of one extra fit() call (fresh tf.function re-trace of
    # the grad step, fit.py:35) — measured, then credited back to every
    # reference timestamp so chunked eval doesn't bill the reference for
    # overhead an unchunked run would not pay
    model.fit(tf_iter=1, newton_iter=0)
    t1 = time.time()
    model.fit(tf_iter=1, newton_iter=0)
    retrace = time.time() - t1
    print(f"[h2h] reference per-fit-call retrace cost: {retrace:.1f}s "
          "(credited back)", flush=True)
    fit_calls = 2
    done = 2

    def t_adj():
        return time.time() - t0 - (fit_calls - 1) * retrace

    while done < adam_iter:
        n = min(ADAM_CHUNK, adam_iter - done)
        model.fit(tf_iter=n, newton_iter=0)
        fit_calls += 1
        done += n
        u_pred, _ = model.predict(X_star)
        record(timeline, t_adj(), rel_l2(np.asarray(u_pred), u_star),
               f"adam@{done}")
    t_lbfgs_start = None
    if newton_iter:
        t_lbfgs_start = t_adj()
        model.fit(tf_iter=0, newton_iter=newton_iter)
        fit_calls += 1
        u_pred, _ = model.predict(X_star)
        record(timeline, t_adj(),
               rel_l2(np.asarray(u_pred), u_star), f"lbfgs@{newton_iter}")
    wall = t_adj()
    ttb = time_to_bar(timeline)
    note = None
    if (ttb is not None and t_lbfgs_start is not None
            and all(p["l2"] > BAR for p in timeline[:-1])
            and timeline[-1]["l2"] <= BAR):
        # only the un-observable L-BFGS phase crossed the bar: credit the
        # reference with crossing at the phase START (lower bound)
        ttb = round(t_lbfgs_start, 1)
        note = ("bar first crossed inside the eager L-BFGS phase (end-only "
                "observable); time_to_bar conservatively set to the phase "
                "start")
    out = {"framework": "reference-tf", "wall": round(wall, 1),
           "retrace_credit_per_call": round(retrace, 1),
           "final_l2": timeline[-1]["l2"],
           "best_l2": min(p["l2"] for p in timeline),
           "time_to_bar": ttb, "timeline": timeline}
    if note:
        out["time_to_bar_note"] = note
    return out


# --------------------------------------------------------------------- #
def run_ours(adam_iter, newton_iter):
    sys.path.insert(0, REPO)
    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import (IC, CollocationSolverND, DomainND,
                                  dirichletBC, grad)

    X_star, u_star = ground_truth()

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], 256)
    domain.add("t", [0.0, 1.0], 100)
    domain.generate_collocation_points(10_000, seed=0)

    bcs = [IC(domain, [lambda x: -np.sin(np.pi * x)], var=[["x"]]),
           dirichletBC(domain, val=0.0, var="x", target="upper"),
           dirichletBC(domain, val=0.0, var="x", target="lower")]

    def f_model(u, x, t):
        u_x, u_t = grad(u, "x"), grad(u, "t")
        u_xx = grad(u_x, "x")
        return u_t(x, t) + u(x, t) * u_x(x, t) - (0.01 / np.pi) * u_xx(x, t)

    # H2H_FUSED picks the residual engine for our arm (public compile()
    # knob; autotune measured the generic jvp engine ~2x faster than the
    # fused Taylor path on CPU for this narrow 20-wide net — the fused
    # engine's batched-matmul layout is an MXU design, round-4 note).
    # Default unchanged (auto).  H2H_EVAL_EVERY tightens the rel-L2
    # sampling grid; evals are included in our clock as always.
    fused_env = os.environ.get("H2H_FUSED", "").lower()
    known = {"": None, "none": None, "auto": None, "false": False,
             "generic": False, "true": True,
             "autotune": "autotune", "pallas": "pallas"}
    if fused_env not in known:  # a typo must not mislabel the artifact
        raise ValueError(f"H2H_FUSED={fused_env!r}; expected one of "
                         f"{sorted(known)}")
    fused = known[fused_env]
    eval_every = int(os.environ.get("H2H_EVAL_EVERY", EVAL_EVERY_OURS))

    solver = CollocationSolverND(verbose=False)
    solver.compile([2] + [20] * 8 + [1], f_model, domain, bcs, fused=fused)

    timeline = []
    t0 = time.time()

    def eval_fn(phase, step, params):
        import jax.numpy as jnp
        u_pred = np.asarray(solver._apply_jit(params,
                                              jnp.asarray(X_star, jnp.float32)))
        record(timeline, time.time() - t0, rel_l2(u_pred, u_star),
               f"{phase}@{step}")

    solver.fit(tf_iter=adam_iter, newton_iter=newton_iter,
               eval_fn=eval_fn, eval_every=eval_every)
    wall = time.time() - t0
    u_pred, _ = solver.predict(X_star, best_model=True)
    best = rel_l2(u_pred, u_star)
    return {"framework": "tensordiffeq-tpu", "wall": round(wall, 1),
            "engine": fused_env or "auto",
            "final_l2": timeline[-1]["l2"],
            "best_l2": min(best, min(p["l2"] for p in timeline)),
            "time_to_bar": time_to_bar(timeline), "timeline": timeline}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--adam", type=int, default=10_000)
    ap.add_argument("--newton", type=int, default=10_000)
    ap.add_argument("--which", choices=("both", "tf", "jax"), default="both")
    args = ap.parse_args()

    config = {"n_f": 10_000, "net": "2-20x8-1",
              "adam": args.adam, "newton": args.newton,
              "bar": BAR, "host": "1 CPU core",
              "truth": "reference burgers_shock.mat 256x100"}
    results = {}
    if os.path.exists(OUT):
        with open(OUT) as fh:
            results = json.load(fh)
        if results.get("config") != config:
            # a config change invalidates cross-run merging — start clean
            # rather than attributing old timelines to the new config
            results = {}
    results["config"] = config

    def save():
        with open(OUT, "w") as fh:
            json.dump(results, fh, indent=1)

    if args.which in ("both", "tf"):
        results["reference-tf"] = run_reference(args.adam, args.newton)
        save()
    if args.which in ("both", "jax"):
        results["tensordiffeq-tpu"] = run_ours(args.adam, args.newton)
        save()

    ours, theirs = results.get("tensordiffeq-tpu"), results.get("reference-tf")
    if ours and theirs and ours.get("time_to_bar") and theirs.get("time_to_bar"):
        results["speedup_to_bar"] = round(
            theirs["time_to_bar"] / ours["time_to_bar"], 2)
        save()
        print(f"[h2h] time-to-{BAR:g}: reference {theirs['time_to_bar']}s, "
              f"ours {ours['time_to_bar']}s -> "
              f"{results['speedup_to_bar']}x", flush=True)
    print(json.dumps({k: {kk: vv for kk, vv in v.items() if kk != "timeline"}
                      if isinstance(v, dict) and "timeline" in v else v
                      for k, v in results.items()}), flush=True)


if __name__ == "__main__":
    main()
