"""End-to-end accuracy of the WHOLE-NET bf16 config (``dtype=bfloat16``
activations, the ``bf16-matmul`` precision-sweep row) vs f32.

The 2026-08-01 precision sweep measured bf16-matmul as the overall
throughput winner (19.46M pts/s, 18.3% MFU) — but ``bench.precision_hint``
deliberately never hints it for the headline because, unlike the fused
``fused_dtype="bfloat16"`` path (f32 accumulation, validated in
``runs/bf16_accuracy.json``), the all-bf16 forward pass has no end-to-end
accuracy evidence.  This run supplies that evidence either way: a
validated win unlocks a ~13% faster headline; a loss is the documented
reason the rule stands.

Same protocol as ``cpu_bf16_accuracy.py``: Burgers, identical
config/seed/budget, rel-L2 vs the Cole-Hopf solution; the f32 arm is
reused from ``runs/bf16_acc_f32.json`` when present.

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
           python scripts/cpu_bf16_net_accuracy.py
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

OUT = os.path.join(ROOT, "runs", "bf16_net_accuracy.json")
N_F, ADAM, NEWTON = 8_192, 4_000, 2_000


def run_bf16_net_arm():
    import jax.numpy as jnp
    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import (CollocationSolverND, DomainND, IC,
                                  dirichletBC, grad, neural_net)
    from tensordiffeq_tpu.exact import burgers_solution

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], 256)
    domain.add("t", [0.0, 1.0], 100)
    domain.generate_collocation_points(N_F, seed=0)
    bcs = [IC(domain, [lambda x: -np.sin(np.pi * x)], var=[["x"]]),
           dirichletBC(domain, 0.0, "x", "upper"),
           dirichletBC(domain, 0.0, "x", "lower")]

    def f_model(u, x, t):
        return (grad(u, "t")(x, t) + u(x, t) * grad(u, "x")(x, t)
                - (0.01 / np.pi) * grad(grad(u, "x"), "x")(x, t))

    layers = [2, 20, 20, 20, 20, 1]
    s = CollocationSolverND(verbose=False)
    # bf16 nets bypass the fused engine (collocation.py: float32-only),
    # exactly as in bench_precision's bf16-matmul row
    s.compile(layers, f_model, domain, bcs,
              network=neural_net(layers, dtype=jnp.bfloat16))
    t0 = time.time()
    s.fit(tf_iter=ADAM, newton_iter=NEWTON)
    wall = time.time() - t0

    x, t, usol = burgers_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = s.predict(Xg, best_model=True)
    l2 = float(tdq.find_L2_error(u_pred, usol.reshape(-1, 1)))
    return {"config": "net dtype=bfloat16 (bf16-matmul row)", "rel_l2": l2,
            "wall_s": round(wall, 1)}


def main():
    results = {}
    f32_part = os.path.join(ROOT, "runs", "bf16_acc_f32.json")
    if os.path.exists(f32_part):
        with open(f32_part) as fh:
            results["f32"] = json.load(fh)
    part = os.path.join(ROOT, "runs", "bf16_acc_netbf16.json")
    if os.path.exists(part):
        with open(part) as fh:
            results["net-bf16"] = json.load(fh)
    else:
        print("[net-bf16] running...", flush=True)
        results["net-bf16"] = run_bf16_net_arm()
        with open(part, "w") as fh:
            json.dump(results["net-bf16"], fh)
    for k, v in results.items():
        print(f"[{k}] rel-L2={v['rel_l2']:.3e}", flush=True)
    f32_l2 = results.get("f32", {}).get("rel_l2")
    net_l2 = results["net-bf16"]["rel_l2"]
    # the validation bar: within 2x of the f32 arm's rel-L2 (the fused
    # bf16 arm landed BETTER than f32; parity-class is what "validated"
    # means, an order-of-magnitude loss is a fail)
    verdict = ("validated" if f32_l2 is not None and net_l2 <= 2 * f32_l2
               else "fails-accuracy")
    out = {"config": f"Burgers N_f={N_F}, 2-20x4-1, {ADAM}+{NEWTON}, seed 0",
           "arms": results, "verdict": verdict,
           "note": "whole-net bf16 (dtype=bfloat16): the bf16-matmul "
                   "precision-sweep row trained end-to-end vs f32"}
    with open(OUT, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "arms"}))


if __name__ == "__main__":
    main()
