#!/bin/bash
# Round-4 CPU evidence queue (serial: ONE core on this host — memory rule:
# never two heavy jobs at once).  Waits for the h2h rerun, then runs the
# VERDICT-priority order: full-grid discovery (V3) -> NTK/causal ablation
# (V6) -> KdV full config (V5).  Every step is idempotent: completed
# artifacts are skipped on re-run, and the per-arm/per-leg checkpoints
# inside each job bound what a kill can lose.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu

while pgrep -f "h2h_rerun_r4.py" > /dev/null; do sleep 60; done

echo "=== A. AC discovery, FULL 512x201 grid, minibatched (12k Adam) ==="
# the reference's own config (AC-discovery.py:14,51-66) needs multi-GPU;
# DiscoveryModel.fit(batch_sz=12864) sweeps the full grid in 8-step
# rotations at the 512x26 run's per-step cost.  no-SA + per-var lr — the
# round-3 converged recipe (also the TPU extras step C config).
if [ -s runs/cpu_discovery_converge_nosa_t1_b12864.json ] \
        || [ -s runs/cpu_discovery_fullgrid_slabbatch.json ]; then
    # done — or attempted and superseded by the permuted-batch rerun,
    # which runs as step E so the VERDICT-priority arms B-D go first
    echo "done/superseded (rerun is step E)"
else
    env DISC_SA=0 DISC_TSUB=1 DISC_BATCH=12864 DISC_ITERS=12000 \
        timeout 21600 nice -n 19 python scripts/cpu_discovery_converge.py \
        > runs/cpu_discovery_fullgrid.log 2>&1
    tail -2 runs/cpu_discovery_fullgrid.log
fi

echo "=== B. NTK + causal weighting vs control (equal budget) ==="
if [ -s runs/weighting_ablation.json ]; then
    echo "done already"
else
    timeout 18000 nice -n 19 python scripts/cpu_weighting_ablation.py \
        > runs/weighting_ablation.log 2>&1
    tail -2 runs/weighting_ablation.log
fi

echo "=== C. KdV soliton FULL config (N_f=20k, 10k+10k) ==="
if grep -aq "relative L2" runs/kdv_full_cpu.log 2>/dev/null; then
    echo "done already"
else
    timeout 21600 nice -n 19 python examples/kdv.py \
        > runs/kdv_full_cpu.log 2>&1
    grep -a "relative L2" runs/kdv_full_cpu.log || tail -2 runs/kdv_full_cpu.log
fi

echo "=== D. bf16 fused engine end-to-end accuracy vs f32 ==="
if [ -s runs/bf16_accuracy.json ]; then
    echo "done already"
else
    timeout 14400 nice -n 19 python scripts/cpu_bf16_accuracy.py \
        > runs/bf16_accuracy.log 2>&1
    tail -2 runs/bf16_accuracy.log
fi

echo "=== E. full-grid discovery RERUN with permuted batches ==="
# step A's first attempt batched contiguous rows — on the meshgrid-ordered
# 512x201 grid each batch was a thin x-slab, and the spatially biased
# gradients oscillated c2 (3.1 -> 1.6 over the last leg;
# runs/cpu_discovery_fullgrid_slabbatch.json is the preserved negative
# result).  DiscoveryModel now permutes the batch index map; rerun.
if [ -s runs/cpu_discovery_converge_nosa_t1_b12864.json ]; then
    echo "done already"
else
    env DISC_SA=0 DISC_TSUB=1 DISC_BATCH=12864 DISC_ITERS=12000 \
        timeout 21600 nice -n 19 python scripts/cpu_discovery_converge.py \
        > runs/cpu_discovery_fullgrid.log 2>&1
    tail -2 runs/cpu_discovery_fullgrid.log
fi

echo "CPU EVIDENCE R4 DONE"
