"""NTK (Adaptive_type=3) and causal weighting vs the non-adaptive control
at EQUAL budget — Allen-Cahn, the stiff benchmark both features target.

Round-3 verdict: both features are implemented and unit-tested but carry
no accuracy evidence ("implemented-but-unproven is the reference's own
NTK story one notch up").  This run closes the loop: three arms on an
identical reduced AC config (same net init seed, same collocation draw,
same Adam+L-BFGS budget), rel-L2 against the spectral fixture.

Arms:
  control — plain MSE, no weighting (the reference's non-adaptive path)
  ntk     — per-term NTK trace balancing, recomputed every chunk
            (the reference DECLARES this mode but ships it dead,
            reference ``models.py:76-84``)
  causal  — causal_eps=1.0, 32 time bins (Wang et al. 2203.07404;
            beyond-reference)

Reduced scale (CPU-core-feasible): N_f=8192, 2-64x3-1, 6k Adam + 2k
L-BFGS.  The interesting quantity is the GAP between arms at equal
budget, which is scale-portable evidence the weighting earns its keep
(the same protocol the round-2 SA-vs-vanilla hedge used).

Crash-safe: each arm writes its own JSON on completion and is skipped on
re-run; the combined table lands in runs/weighting_ablation.json.

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
           python scripts/cpu_weighting_ablation.py
"""
import json
import os
import sys
import time

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "examples"))

N_F = 8_192
WIDTHS = [64, 64, 64]
ADAM, NEWTON = 6_000, 2_000
OUT = os.path.join(ROOT, "runs", "weighting_ablation.json")


def run_arm(name):
    import numpy as np

    from ac_baseline import build_problem

    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import CollocationSolverND
    from tensordiffeq_tpu.exact import allen_cahn_solution

    domain, bcs, f_model = build_problem(N_F, nx=512, nt=201, seed=0)
    kw = {}
    if name == "ntk":
        kw = dict(Adaptive_type=3)
    elif name == "causal":
        kw = dict(causal_eps=1.0, causal_bins=32)
    elif name == "causal_lo":
        # budget-sensitivity probe: the eps=1.0 arm measured the gate
        # starving late-time training inside 6k iters (AC early losses are
        # O(1e2), so exp(-eps*cumsum) ~ 0); a small fixed eps opens the
        # horizon earlier — the cheap stand-in for the paper's eps
        # annealing schedule
        kw = dict(causal_eps=0.02, causal_bins=32)
    elif name == "causal_anneal":
        # round 5: the REAL paper schedule (2203.07404 Alg. 1) — the full
        # ladder, each stage advancing when the gate opens (w_last>0.99).
        # Same seed/draw/budget as every other arm, so the r4 fixed-eps
        # results (causal 6.52e-1, causal_lo 9.90e-1, control 5.89e-1)
        # are directly comparable
        kw = dict(causal_eps=[0.01, 0.1, 1.0, 10.0, 100.0],
                  causal_bins=32)

    solver = CollocationSolverND(verbose=False)
    solver.compile([2, *WIDTHS, 1], f_model, domain, bcs, **kw)
    t0 = time.time()
    solver.fit(tf_iter=ADAM, newton_iter=NEWTON)
    wall = time.time() - t0

    x, t, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = solver.predict(Xg, best_model=True)
    l2 = float(tdq.find_L2_error(u_pred, usol.reshape(-1, 1)))
    return {"arm": name, "rel_l2": l2, "wall_s": round(wall, 1),
            "config": f"AC N_f={N_F}, 2-64x3-1, {ADAM}+{NEWTON}, seed 0"}


def main():
    results = {}
    arms = ["control", "ntk", "causal"]
    if os.environ.get("ABLATION_EXTRA"):
        arms += os.environ["ABLATION_EXTRA"].split(",")
    for name in arms:
        part = os.path.join(ROOT, "runs", f"weighting_{name}.json")
        if os.path.exists(part):
            with open(part) as fh:
                results[name] = json.load(fh)
            print(f"[{name}] cached: rel-L2={results[name]['rel_l2']:.3e}",
                  flush=True)
            continue
        print(f"[{name}] running...", flush=True)
        results[name] = run_arm(name)
        with open(part, "w") as fh:
            json.dump(results[name], fh)
        print(f"[{name}] rel-L2={results[name]['rel_l2']:.3e} "
              f"({results[name]['wall_s']:.0f}s)", flush=True)

    ctrl = results["control"]["rel_l2"]
    out = {"arms": results}
    for name in results:
        if name != "control":
            out[f"{name}_gain_vs_control"] = round(
                ctrl / results[name]["rel_l2"], 3)
    with open(OUT, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "arms"}),
          flush=True)


if __name__ == "__main__":
    main()
