"""NTK weighting vs control on Helmholtz — the feature's home turf.

The AC arm of the round-4 ablation showed NTK per-TERM balancing cannot
fix Allen-Cahn (control 5.89e-1 vs ntk 6.02e-1 at equal budget): AC's
failure mode is per-POINT stiffness, which only the SA minimax targets
(12.5x gap, CONVERGENCE.md).  NTK's own claim (Wang et al. 2007.14527)
is about balancing loss-term SCALES on smooth boundary-value problems —
Helmholtz with a high-frequency forcing is the canonical case: the BC
terms and the (much larger) residual term live at very different scales.
Two arms, identical config/seed/budget, rel-L2 vs the analytic solution.

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
           python scripts/cpu_ntk_helmholtz.py
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

OUT = os.path.join(ROOT, "runs", "ntk_helmholtz.json")
N_F, ADAM, NEWTON = 8_192, 5_000, 2_000
A1, A2, KSQ = 1.0, 4.0, 1.0


def run_arm(ntk: bool, max_points: int = 256, adam: int = ADAM,
            newton: int = NEWTON):
    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import CollocationSolverND, DomainND, dirichletBC, \
        grad

    domain = DomainND(["x", "y"])
    domain.add("x", [-1.0, 1.0], 501)
    domain.add("y", [-1.0, 1.0], 501)
    domain.generate_collocation_points(N_F, seed=0)
    bcs = [dirichletBC(domain, val=0.0, var=v, target=tg)
           for v in ("x", "y") for tg in ("upper", "lower")]

    def f_model(u, x, y):
        import jax.numpy as jnp
        pi = np.pi
        s = jnp.sin(A1 * pi * x) * jnp.sin(A2 * pi * y)
        forcing = (-(A1 * pi) ** 2 - (A2 * pi) ** 2 + KSQ) * s
        return (grad(grad(u, "x"), "x")(x, y)
                + grad(grad(u, "y"), "y")(x, y) + KSQ * u(x, y) - forcing)

    solver = CollocationSolverND(verbose=False)
    solver.compile([2, 32, 32, 32, 1], f_model, domain, bcs,
                   **(dict(Adaptive_type=3, ntk_max_points=max_points)
                      if ntk else {}))
    t0 = time.time()
    solver.fit(tf_iter=adam, newton_iter=newton)
    wall = time.time() - t0

    n = 201
    xv, yv = np.meshgrid(np.linspace(-1, 1, n), np.linspace(-1, 1, n))
    exact = np.sin(A1 * np.pi * xv) * np.sin(A2 * np.pi * yv)
    Xg = np.hstack([xv.reshape(-1, 1), yv.reshape(-1, 1)])
    u_pred, _ = solver.predict(Xg, best_model=True)
    l2 = float(tdq.find_L2_error(u_pred, exact.reshape(-1, 1)))
    out = {"arm": "ntk" if ntk else "control", "rel_l2": l2,
           "wall_s": round(wall, 1),
           "config": f"Helmholtz N_f={N_F}, 2-32x3-1, {adam}+{newton}"}
    if ntk:
        # the quantity the sensitivity question is about: the final
        # per-term λ balance the traces produced
        out["max_points"] = max_points
        out["lambda_bcs"] = [None if v is None else float(np.ravel(v)[0])
                             for v in solver.lambdas["BCs"]]
        out["lambda_res"] = [None if v is None else float(np.ravel(v)[0])
                             for v in solver.lambdas["residual"]]
    return out


def sensitivity():
    """NTK trace-subsample sensitivity (VERDICT r4 weak #5): identical
    seed/config arms at max_points 256/512/1024, reduced budget — the
    deliverable is λ-balance and rel-L2 STABILITY across subsample sizes,
    not absolute accuracy (the 5k+2k headline above covers that)."""
    adam, newton = 2_000, 1_000
    results = {}
    for mp in (256, 512, 1024):
        part = os.path.join(ROOT, "runs", f"ntk_helm_mp{mp}.json")
        if os.path.exists(part):
            with open(part) as fh:
                results[mp] = json.load(fh)
        else:
            print(f"[mp{mp}] running...", flush=True)
            results[mp] = run_arm(True, max_points=mp,
                                  adam=adam, newton=newton)
            with open(part, "w") as fh:
                json.dump(results[mp], fh)
        print(f"[mp{mp}] rel-L2={results[mp]['rel_l2']:.3e} "
              f"lam_res={results[mp]['lambda_res']}", flush=True)
    base = results[256]
    out = {"arms": {str(k): v for k, v in results.items()},
           "rel_l2_spread": round(
               max(r["rel_l2"] for r in results.values())
               / min(r["rel_l2"] for r in results.values()), 3),
           "lambda_res_ratio_vs_256": {
               str(mp): round(results[mp]["lambda_res"][0]
                              / base["lambda_res"][0], 3)
               for mp in results}}
    with open(os.path.join(ROOT, "runs", "ntk_sensitivity.json"), "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "arms"}),
          flush=True)


def main():
    results = {}
    for name, flag in (("control", False), ("ntk", True)):
        part = os.path.join(ROOT, "runs", f"ntk_helm_{name}.json")
        if os.path.exists(part):
            with open(part) as fh:
                results[name] = json.load(fh)
        else:
            print(f"[{name}] running...", flush=True)
            results[name] = run_arm(flag)
            with open(part, "w") as fh:
                json.dump(results[name], fh)
        print(f"[{name}] rel-L2={results[name]['rel_l2']:.3e}", flush=True)
    out = {"arms": results,
           "ntk_gain_vs_control":
               round(results["control"]["rel_l2"]
                     / results["ntk"]["rel_l2"], 3)}
    with open(OUT, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "arms"}),
          flush=True)


if __name__ == "__main__":
    if "--sens" in sys.argv:
        sensitivity()
    else:
        main()
