"""NTK weighting vs control on Helmholtz — the feature's home turf.

The AC arm of the round-4 ablation showed NTK per-TERM balancing cannot
fix Allen-Cahn (control 5.89e-1 vs ntk 6.02e-1 at equal budget): AC's
failure mode is per-POINT stiffness, which only the SA minimax targets
(12.5x gap, CONVERGENCE.md).  NTK's own claim (Wang et al. 2007.14527)
is about balancing loss-term SCALES on smooth boundary-value problems —
Helmholtz with a high-frequency forcing is the canonical case: the BC
terms and the (much larger) residual term live at very different scales.
Two arms, identical config/seed/budget, rel-L2 vs the analytic solution.

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
           python scripts/cpu_ntk_helmholtz.py
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

OUT = os.path.join(ROOT, "runs", "ntk_helmholtz.json")
N_F, ADAM, NEWTON = 8_192, 5_000, 2_000
A1, A2, KSQ = 1.0, 4.0, 1.0


def run_arm(ntk: bool):
    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import CollocationSolverND, DomainND, dirichletBC, \
        grad

    domain = DomainND(["x", "y"])
    domain.add("x", [-1.0, 1.0], 501)
    domain.add("y", [-1.0, 1.0], 501)
    domain.generate_collocation_points(N_F, seed=0)
    bcs = [dirichletBC(domain, val=0.0, var=v, target=tg)
           for v in ("x", "y") for tg in ("upper", "lower")]

    def f_model(u, x, y):
        import jax.numpy as jnp
        pi = np.pi
        s = jnp.sin(A1 * pi * x) * jnp.sin(A2 * pi * y)
        forcing = (-(A1 * pi) ** 2 - (A2 * pi) ** 2 + KSQ) * s
        return (grad(grad(u, "x"), "x")(x, y)
                + grad(grad(u, "y"), "y")(x, y) + KSQ * u(x, y) - forcing)

    solver = CollocationSolverND(verbose=False)
    solver.compile([2, 32, 32, 32, 1], f_model, domain, bcs,
                   **(dict(Adaptive_type=3) if ntk else {}))
    t0 = time.time()
    solver.fit(tf_iter=ADAM, newton_iter=NEWTON)
    wall = time.time() - t0

    n = 201
    xv, yv = np.meshgrid(np.linspace(-1, 1, n), np.linspace(-1, 1, n))
    exact = np.sin(A1 * np.pi * xv) * np.sin(A2 * np.pi * yv)
    Xg = np.hstack([xv.reshape(-1, 1), yv.reshape(-1, 1)])
    u_pred, _ = solver.predict(Xg, best_model=True)
    l2 = float(tdq.find_L2_error(u_pred, exact.reshape(-1, 1)))
    return {"arm": "ntk" if ntk else "control", "rel_l2": l2,
            "wall_s": round(wall, 1),
            "config": f"Helmholtz N_f={N_F}, 2-32x3-1, {ADAM}+{NEWTON}"}


def main():
    results = {}
    for name, flag in (("control", False), ("ntk", True)):
        part = os.path.join(ROOT, "runs", f"ntk_helm_{name}.json")
        if os.path.exists(part):
            with open(part) as fh:
                results[name] = json.load(fh)
        else:
            print(f"[{name}] running...", flush=True)
            results[name] = run_arm(flag)
            with open(part, "w") as fh:
                json.dump(results[name], fh)
        print(f"[{name}] rel-L2={results[name]['rel_l2']:.3e}", flush=True)
    out = {"arms": results,
           "ntk_gain_vs_control":
               round(results["control"]["rel_l2"]
                     / results["ntk"]["rel_l2"], 3)}
    with open(OUT, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "arms"}),
          flush=True)


if __name__ == "__main__":
    main()
