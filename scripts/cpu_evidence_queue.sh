#!/bin/bash
# CPU-side evidence queue (runs while/if the TPU tunnel is down): waits for
# any earlier CPU job to finish, then full-size steady-state convergence
# runs — these configs fit a single CPU core (BASELINE.md scale).
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu

while pgrep -f "cpu_ac_sa_reduced.py|resample_ablation.py" > /dev/null; do
    sleep 120
done

echo "=== Poisson steady-state (full: N_f=100 grid, 4000 Adam) ==="
timeout 3600 nice -n 10 python examples/steady_state_poisson.py \
    > runs/poisson_full_cpu.log 2>&1
grep -a "Error u" runs/poisson_full_cpu.log || tail -2 runs/poisson_full_cpu.log

echo "=== Helmholtz steady-state (full: N_f=10k, 10k Adam + 10k L-BFGS) ==="
timeout 7200 nice -n 10 python examples/steady_state_helmholtz.py \
    > runs/helmholtz_full_cpu.log 2>&1
grep -a "Error u" runs/helmholtz_full_cpu.log || tail -2 runs/helmholtz_full_cpu.log

echo "CPU EVIDENCE QUEUE DONE"
