"""CPU hedge: reduced Allen-Cahn SA-PINN convergence run.

When the TPU tunnel is down for the whole round, this still demonstrates
the SA-PINN minimax dynamics converging on Allen-Cahn (SURVEY §7 "hard
part (b)") at a config one CPU core can finish: N_f=10k, 2-64x3-1,
10k Adam + 10k L-BFGS, with the non-adaptive control at the same budget.
The SA-PINN paper's point (arXiv:2009.04544, cited at reference
models.py:37) is that vanilla PINNs fail on Allen-Cahn (rel-L2 ~0.51)
while SA weights make it trainable — the reduced pair shows exactly that
gap.  Full-size TPU numbers land separately via scripts/tpu_evidence.sh.

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/cpu_ac_sa_reduced.py
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "examples"))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

N_F, NX, NT = 10_000, 512, 201
WIDTHS = [64, 64, 64]
ADAM, NEWTON = 10_000, 10_000


def run(adaptive: bool):
    from ac_baseline import build_problem

    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import CollocationSolverND
    from tensordiffeq_tpu.exact import allen_cahn_solution

    domain, bcs, f_model = build_problem(N_F, nx=NX, nt=NT)
    solver = CollocationSolverND(verbose=False)
    kw = {}
    if adaptive:
        rng = np.random.RandomState(0)
        kw = dict(Adaptive_type=1,
                  dict_adaptive={"residual": [True], "BCs": [True, False]},
                  init_weights={"residual": [rng.rand(N_F, 1)],
                                "BCs": [100.0 * rng.rand(NX, 1), None]})
    solver.compile([2, *WIDTHS, 1], f_model, domain, bcs, **kw)
    t0 = time.time()
    solver.fit(tf_iter=ADAM, newton_iter=NEWTON)
    wall = time.time() - t0

    x, t, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = solver.predict(Xg, best_model=True)
    err = float(tdq.find_L2_error(u_pred, usol.reshape(-1, 1)))
    return {"adaptive": adaptive, "rel_l2": err, "wall_s": round(wall, 1),
            "config": f"N_f={N_F}, 2-{'x'.join(map(str, WIDTHS))}-1, "
                      f"{ADAM} Adam + {NEWTON} L-BFGS"}


if __name__ == "__main__":
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "runs", "cpu_ac_sa_reduced.json")
    out = []
    for adaptive in (True, False):
        r = run(adaptive)
        out.append(r)
        print(json.dumps(r), flush=True)
        # dump after EVERY variant: a killed control run must not lose the
        # already-finished adaptive result
        with open(path, "w") as fh:
            json.dump(out, fh, indent=1)
