#!/bin/bash
# Re-capture the CPU evidence logs that CONVERGENCE.md cites but which
# were lost to environment resets (the blanket `*.log` gitignore meant
# earlier rounds never committed them; fixed 2026-08-01 with `!runs/*.log`).
# Rows reproducible from example defaults (Poisson, Helmholtz) re-run
# as-is; the reduced KdV/NLS rows re-run via the examples' CLI overrides
# set to the recorded rows' exact configs.  nice 19 so a live TPU-window
# orchestration always wins the core; idempotent via success markers.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
export TDQ_PLATFORM=cpu JAX_PLATFORMS=cpu

step() {  # step <log> <marker> <cmd...>
    local log=$1 marker=$2; shift 2
    if [ -s "$log" ] && grep -aq "$marker" "$log"; then
        echo "skip $log (already captured)"; return
    fi
    echo "=== $log ==="
    nice -n 19 "$@" > "$log" 2>&1
    grep -a "$marker" "$log" || tail -3 "$log"
}

# Poisson steady state: reference's own Adam-only config on a 100-pt grid
step runs/poisson_full_cpu.log "Error u" \
    timeout 3600 python examples/steady_state_poisson.py

# Helmholtz full (N_f=10k, 2-50x4-1, 10k Adam + L-BFGS)
step runs/helmholtz_full_cpu.log "Error u" \
    timeout 21600 python examples/steady_state_helmholtz.py

# KdV reduced (N_f=8k, 2-30x4-1, 4k+3k — the recorded row's exact config)
step runs/kdv_reduced_cpu.log "relative L2" \
    timeout 14400 python examples/kdv.py --nf 8000 --adam 4000 --newton 3000

# NLS reduced (N_f=8k, 2-64x4-2, 5k+5k — the recorded row's exact config)
step runs/nls_reduced_cpu.log "Error u" \
    timeout 21600 python examples/schrodinger.py --nf 8000 --width 64 \
        --adam 5000 --newton 5000

echo "cpu recapture queue done $(date -u)"
