"""End-to-end accuracy of the bf16 fused engine vs f32 at equal budget.

The bf16 single-pass MXU path is the framework's MFU lever (PERF.md
roofline); its one-step loss drift is measured at 9.2e-5, but no
CONVERGENCE row shows a full training run landing at the same rel-L2.
This closes that: Burgers, identical config/seed, one arm
``fused_dtype="bfloat16"`` (Adam phase on bf16 matmuls; the L-BFGS phase
auto-runs f32 — the documented design), one arm full f32.  The deliverable
is the rel-L2 GAP, which is backend-portable evidence the precision mode
is a real training configuration, not a throughput-only stunt.

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
           python scripts/cpu_bf16_accuracy.py
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

OUT = os.path.join(ROOT, "runs", "bf16_accuracy.json")
N_F, ADAM, NEWTON = 8_192, 4_000, 2_000


def run_arm(fused_dtype):
    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import (CollocationSolverND, DomainND, IC,
                                  dirichletBC, grad)
    from tensordiffeq_tpu.exact import burgers_solution

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], 256)
    domain.add("t", [0.0, 1.0], 100)
    domain.generate_collocation_points(N_F, seed=0)
    bcs = [IC(domain, [lambda x: -np.sin(np.pi * x)], var=[["x"]]),
           dirichletBC(domain, 0.0, "x", "upper"),
           dirichletBC(domain, 0.0, "x", "lower")]

    def f_model(u, x, t):
        return (grad(u, "t")(x, t) + u(x, t) * grad(u, "x")(x, t)
                - (0.01 / np.pi) * grad(grad(u, "x"), "x")(x, t))

    s = CollocationSolverND(verbose=False)
    s.compile([2, 20, 20, 20, 20, 1], f_model, domain, bcs,
              fused=True, fused_dtype=fused_dtype)
    t0 = time.time()
    s.fit(tf_iter=ADAM, newton_iter=NEWTON)
    wall = time.time() - t0

    x, t, usol = burgers_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = s.predict(Xg, best_model=True)
    l2 = float(tdq.find_L2_error(u_pred, usol.reshape(-1, 1)))
    return {"fused_dtype": fused_dtype or "float32", "rel_l2": l2,
            "wall_s": round(wall, 1)}


def main():
    results = {}
    for name, dt in (("f32", None), ("bf16", "bfloat16")):
        part = os.path.join(ROOT, "runs", f"bf16_acc_{name}.json")
        if os.path.exists(part):
            with open(part) as fh:
                results[name] = json.load(fh)
        else:
            print(f"[{name}] running...", flush=True)
            results[name] = run_arm(dt)
            with open(part, "w") as fh:
                json.dump(results[name], fh)
        print(f"[{name}] rel-L2={results[name]['rel_l2']:.3e}", flush=True)
    out = {"config": f"Burgers N_f={N_F}, 2-20x4-1, {ADAM}+{NEWTON}, seed 0",
           "arms": results,
           "bf16_over_f32_l2_ratio":
               round(results["bf16"]["rel_l2"] / results["f32"]["rel_l2"], 3)}
    with open(OUT, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "arms"}),
          flush=True)


if __name__ == "__main__":
    main()
