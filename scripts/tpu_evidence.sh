#!/bin/bash
# Round-2 TPU evidence queue: run the full measurement suite the moment the
# TPU tunnel is healthy.  Each step is independent; artifacts land in
# runs/ and BENCH_TPU_*.json at the repo root.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs

echo "=== 0. health check ==="
timeout 90 python -c "import jax; print(jax.devices())" || exit 1

echo "=== 1. AC-SA full convergence (10k Adam + 10k L-BFGS) ==="
BENCH_TIMEOUT=5400 timeout 5500 python bench.py --full \
    > BENCH_TPU_full.json 2> runs/ac_sa_full_tpu.log
tail -1 BENCH_TPU_full.json

echo "=== 2. headline throughput (autotune now includes pallas) ==="
timeout 1800 python bench.py > BENCH_TPU_default.json 2> runs/bench_default_tpu.log
tail -1 BENCH_TPU_default.json

echo "=== 3. precision axis (incl bf16-taylor) ==="
timeout 2500 python bench.py --precision > BENCH_TPU_precision.json 2> runs/bench_precision_tpu.log
tail -1 BENCH_TPU_precision.json

echo "=== 4. engines ==="
timeout 1800 python bench.py --engines > BENCH_TPU_engines.json 2> runs/bench_engines_tpu.log
tail -1 BENCH_TPU_engines.json

echo "=== 5. on-hardware kernel parity tests ==="
timeout 1200 python -m pytest hwtests/ -q 2>&1 | tail -3 | tee runs/hwtests_tpu.log

echo "ALL TPU EVIDENCE CAPTURED"
