#!/bin/bash
# Round-2 TPU evidence queue: run the full measurement suite the moment the
# TPU tunnel is healthy.  Each step is independent; artifacts land in
# runs/ and BENCH_TPU_*.json at the repo root.
#
# Results are written to runs/<name>.new first and only promoted to the
# canonical BENCH_TPU_<name>.json when they are real TPU measurements —
# bench.py falls back to CPU when the tunnel dies mid-suite, and a
# cpu-fallback line must never clobber a previously captured TPU artifact.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
. scripts/_promote.sh

echo "=== 0. health check ==="
timeout 90 python -c "import jax; print(jax.devices())" || exit 1

echo "=== 1. AC-SA full convergence (10k Adam + 10k L-BFGS) ==="
BENCH_TIMEOUT=5400 timeout 5500 python bench.py --full \
    > runs/full.new 2> runs/ac_sa_full_tpu.log
promote full

echo "=== 2. headline throughput (autotune now includes pallas) ==="
timeout 1800 python bench.py > runs/default.new 2> runs/bench_default_tpu.log
promote default

echo "=== 3. precision axis (incl bf16-taylor) ==="
timeout 2500 python bench.py --precision > runs/precision.new 2> runs/bench_precision_tpu.log
promote precision

echo "=== 4. engines ==="
timeout 1800 python bench.py --engines > runs/engines.new 2> runs/bench_engines_tpu.log
promote engines

echo "=== 5. on-hardware kernel parity tests ==="
timeout 1200 python -m pytest hwtests/ -q 2>&1 | tail -3 | tee runs/hwtests_tpu.log

echo "ALL TPU EVIDENCE CAPTURED"
