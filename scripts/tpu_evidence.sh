#!/bin/bash
# TPU evidence queue: run the full measurement suite the moment the TPU
# tunnel is healthy.  Each step is independent AND idempotent — a step
# whose canonical artifact already exists is skipped, so the watcher can
# re-pass after a mid-suite tunnel death and only fill the gaps.
#
# ORDER (round-3): headline capture first (~8-10 min with the cached TF
# baseline), then the north-star AC-SA time-to-L2 run — if the tunnel
# yields exactly one good window it must land those two, not the short
# secondary captures.  The AC-SA run streams per-eval snapshots so even a
# truncated window salvages a partial; precision/engines/hwtests follow.
#
# Results are written to runs/<name>.new first and only promoted to the
# canonical BENCH_TPU_<name>.json when they are real TPU measurements
# (scripts/_promote.sh): bench.py falls back to CPU when the tunnel dies
# mid-suite, and a cpu-fallback line must never clobber a TPU artifact.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
. scripts/_promote.sh

# CPU fallbacks can't be promoted — never burn tunnel-window minutes on
# them from the watcher (round-3 lesson: a dead tunnel turned each step
# into a 25-90 min CPU measurement that promote() then rejected)
export BENCH_NO_CPU_FALLBACK=1

echo "=== 0. health check ==="
timeout 90 python -c "import jax; print(jax.devices())" || exit 1

echo "=== 1. headline throughput (autotune now includes pallas) ==="
# always re-run: the tracked artifact predates the pallas autotune fix, and
# promote() only replaces it with a real TPU measurement.  The watcher run
# gets a bigger budget than the driver default (1140s): pallas-inclusive
# autotune plus the AOT compile is ~8-12 min of compiles through the tunnel.
BENCH_BUDGET=1700 timeout 1800 python bench.py \
    > runs/default.new 2> runs/bench_default_tpu.log
promote default

echo "=== 2. AC-SA full convergence (10k Adam + 10k L-BFGS) — north star ==="
# Runs SECOND (round-3 reorder): if the tunnel yields exactly one good
# window this round, it must land the time-to-L2 artifact, not four short
# captures.  Streamed per-eval snapshots make a truncated run salvageable.
# BENCH_BUDGET sits inside the outer timeout so bench.py always gets to
# print its JSON line (and salvage streamed partials) before the kill.
if have_complete full; then echo "already captured"; else
    BENCH_BUDGET=5300 BENCH_TIMEOUT=5100 timeout 5500 python bench.py --full \
        > runs/full.new 2> runs/ac_sa_full_tpu.log
    promote full
fi

echo "=== 3. precision axis (incl bf16-taylor + bf16-pallas) ==="
if have_complete precision; then echo "already captured"; else
    BENCH_BUDGET=2300 timeout 2500 python bench.py --precision \
        > runs/precision.new 2> runs/bench_precision_tpu.log
    promote precision
fi

echo "=== 4. engines ==="
# always re-run (old artifact lacks the backend field); promote-gated
BENCH_BUDGET=1700 timeout 1800 python bench.py --engines \
    > runs/engines.new 2> runs/bench_engines_tpu.log
promote engines

echo "=== 5. on-hardware kernel parity tests ==="
if [ -s runs/hwtests_tpu.log ] && grep -q "passed" runs/hwtests_tpu.log; then
    echo "already captured"
elif timeout 120 python -c "
import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null; then
    timeout 1200 python -m pytest hwtests/ -q 2>&1 | tail -3 | tee runs/hwtests_tpu.log
else
    # a wedged tunnel would hang pytest's backend init for the full
    # timeout; skip and let a later watcher pass retry
    echo "SKIP: tunnel unhealthy"
fi

echo "ALL TPU EVIDENCE CAPTURED"
