#!/bin/bash
# TPU evidence queue: run the full measurement suite the moment the TPU
# tunnel is healthy.  Each step is independent AND idempotent — a step
# whose canonical artifact already exists is skipped, so the watcher can
# re-pass after a mid-suite tunnel death and only fill the gaps.
#
# ORDER (round-4, per VERDICT): the north-star AC-SA time-to-L2 run goes
# FIRST — the headline throughput is already cached and loses little by
# aging, while the full-size convergence artifact is the single number the
# project exists to produce.  The AC-SA run streams per-eval snapshots so
# even a truncated window salvages a partial.  Then the precision axis
# (bf16 MFU — the measured lever), then the engine-hinted headline
# refresh (fast: the promoted engines artifact skips autotune), engines,
# hwtests.  The persistent XLA compile cache (utils.enable_compilation_
# cache, round 4) makes every re-pass cheaper than the last.
#
# Results are written to runs/<name>.new first and only promoted to the
# canonical BENCH_TPU_<name>.json when they are real TPU measurements
# (scripts/_promote.sh): bench.py falls back to CPU when the tunnel dies
# mid-suite, and a cpu-fallback line must never clobber a TPU artifact.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
. scripts/_promote.sh

# CPU fallbacks can't be promoted — never burn tunnel-window minutes on
# them from the watcher (round-3 lesson: a dead tunnel turned each step
# into a 25-90 min CPU measurement that promote() then rejected)
export BENCH_NO_CPU_FALLBACK=1

echo "=== 0. health check ==="
timeout 90 python -c "import jax; print(jax.devices())" || exit 1

echo "=== 1. AC-SA full convergence (10k Adam + 10k L-BFGS) — north star ==="
# Runs FIRST (round-4 reorder, per the judge): if the tunnel yields exactly
# one good window this round, it must land the time-to-L2 artifact.
# Streamed per-eval snapshots make a truncated run salvageable.
# BENCH_BUDGET sits inside the outer timeout so bench.py always gets to
# print its JSON line (and salvage streamed partials) before the kill.
if have_complete full; then echo "already captured"; else
    BENCH_BUDGET=5300 BENCH_TIMEOUT=5100 timeout 5500 python bench.py --full \
        > runs/full.new 2> runs/ac_sa_full_tpu.log
    promote full
fi

echo "=== 2. precision axis (incl bf16-taylor + bf16-pallas) ==="
# the bf16 single-pass MXU path is the measured MFU lever (PERF.md
# roofline); its hardware capture is round-4 priority #2
# re-run while the artifact carries a known-bad MFU row (mfu_note: the
# 2026-08-01 capture predates the pallas-blind flop-basis fix)
if have_complete precision \
        && ! grep -q '"mfu_note"' BENCH_TPU_precision.json; then
    echo "already captured"; else
    BENCH_BUDGET=2300 timeout 2500 python bench.py --precision \
        > runs/precision.new 2> runs/bench_precision_tpu.log
    promote precision
fi

echo "=== 3. headline throughput (engine-hinted: skips autotune) ==="
# re-run until the artifact was promoted AFTER the precision artifact it
# takes its hint from (mtime ordering — the in-file "captured" field is
# day-granular and cannot order two same-day captures; `-nt` is also true
# when no precision artifact exists, i.e. no hint source to refresh
# against).  After that a re-pass has nothing to add and the window
# minutes go to extras.  Worst case after a fresh git checkout equalises
# mtimes: one redundant (cheap, engine-hinted) headline run re-orders them.
if have_complete default && ! grep -q '"mfu_note"' BENCH_TPU_default.json \
        && [ BENCH_TPU_default.json -nt BENCH_TPU_precision.json ]; then
    echo "already captured (headline newer than its precision hint source)"
else
    BENCH_BUDGET=1700 timeout 1800 python bench.py \
        > runs/default.new 2> runs/bench_default_tpu.log
    promote default
fi

echo "=== 4. engines ==="
# re-run until the artifact carries the backend field (pre-round-5 ones
# lacked it); promote-gated
if have_complete engines \
        && grep -q '"backend": "tpu"' BENCH_TPU_engines.json; then
    echo "already captured"
else
    BENCH_BUDGET=1700 timeout 1800 python bench.py --engines \
        > runs/engines.new 2> runs/bench_engines_tpu.log
    promote engines
fi

echo "=== 4b. scale sweep (N_f 50k -> 500k single chip) ==="
# VERDICT r4 #4: prove one v5e chip absorbs the reference's multi-GPU
# config (AC-dist-new.py N_f=500k), with the remat HBM trade measured
# (bench_scale retries OOM points with remat=True)
if have_complete scale; then echo "already captured"; else
    BENCH_BUDGET=2300 timeout 2500 python bench.py --scale \
        > runs/scale.new 2> runs/bench_scale_tpu.log
    promote scale
fi

echo "=== 4c. remat trade (N_f=50k/500k, remat off vs on) ==="
# VERDICT r4 #4 tail: the remat HBM-for-FLOPs trade measured, not asserted
if have_complete remat; then echo "already captured"; else
    BENCH_BUDGET=2300 timeout 2500 python bench.py --remat \
        > runs/remat.new 2> runs/bench_remat_tpu.log
    promote remat
fi

echo "=== 5. on-hardware kernel parity tests ==="
if [ -s runs/hwtests_tpu.log ] && grep -q "passed" runs/hwtests_tpu.log; then
    echo "already captured"
elif timeout 120 python -c "
import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null; then
    timeout 1200 python -m pytest hwtests/ -q 2>&1 | tail -3 | tee runs/hwtests_tpu.log
else
    # a wedged tunnel would hang pytest's backend init for the full
    # timeout; skip and let a later watcher pass retry
    echo "SKIP: tunnel unhealthy"
fi

echo "ALL TPU EVIDENCE CAPTURED"
