#!/bin/bash
# Quiescent snapshot of the live AC-SA full CPU-hedge checkpoint.
#
# The live dir (runs/ac_sa_full_cpu_ckpt, gitignored) is rewritten every
# eval boundary; copying it mid-write could ship a torn orbax manifest.
# SIGSTOP the trainer, copy, SIGCONT — the copy is guaranteed consistent
# (save_checkpoint's atomic swap means the dir is always either the old
# or the new complete state while the process is stopped).  The snapshot
# (runs/hedge_r5_ckpt) is committed so the next round can resume the run
# via BENCH_FULL_CKPT=runs/hedge_r5_ckpt (or by copying it back).
set -u
cd "$(dirname "$0")/.."
pid=$(pgrep -f cpu_ac_sa_full.py | head -1)
[ -n "${pid:-}" ] && kill -STOP "$pid"
trap '[ -n "${pid:-}" ] && kill -CONT "$pid"' EXIT
src=runs/ac_sa_full_cpu_ckpt
# killed-mid-swap fallback: the parked .old is the restorable one
if [ ! -f "$src/tdq_meta.json" ] && [ -f "$src.old/tdq_meta.json" ]; then
    src=$src.old
fi
if [ ! -f "$src/tdq_meta.json" ]; then
    echo "no restorable hedge checkpoint found" >&2
    exit 1
fi
rm -rf runs/hedge_r5_ckpt
cp -r "$src" runs/hedge_r5_ckpt
echo "snapshot: $(du -sh runs/hedge_r5_ckpt | cut -f1) from $src"
