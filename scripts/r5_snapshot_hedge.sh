#!/bin/bash
# Quiescent snapshot of the live AC-SA full CPU-hedge checkpoint.
#
# The live dir (runs/ac_sa_full_cpu_ckpt, gitignored) is rewritten every
# eval boundary; copying it mid-write could ship a torn orbax manifest.
# SIGSTOP the trainer, copy, SIGCONT — the copy is guaranteed consistent
# (save_checkpoint's atomic swap means the dir is always either the old
# or the new complete state while the process is stopped).  The snapshot
# (runs/hedge_r5_ckpt) is committed so the next round can resume the run
# via BENCH_FULL_CKPT=runs/hedge_r5_ckpt (or by copying it back).
set -u
cd "$(dirname "$0")/.."
# match the python writer only (a bash wrapper/tail whose cmdline contains
# the filename must not be the thing we STOP), and install the CONT restore
# BEFORE stopping — an EXIT-only trap set after the STOP leaves the trainer
# frozen forever if this script dies in between or on a signal
# anchored to the start of the cmdline: a `bash -c 'python ...'` wrapper's
# cmdline CONTAINS the python invocation but does not START with it, and
# stopping the wrapper instead of the writer would copy a live dir
pid=$(pgrep -f '^[^ ]*python[0-9.]* .*cpu_ac_sa_full\.py' | head -1)
trap '[ -n "${pid:-}" ] && kill -CONT "$pid" 2>/dev/null' EXIT
# a signal must RESUME AND STOP COPYING — falling through to cp after
# SIGCONT would snapshot a live-rewritten dir, the torn state this script
# exists to prevent (the EXIT trap's second kill -CONT is harmless)
trap 'exit 130' INT TERM HUP
[ -n "${pid:-}" ] && kill -STOP "$pid"
src=runs/ac_sa_full_cpu_ckpt
# killed-mid-swap fallback: the parked .old is the restorable one
if [ ! -f "$src/tdq_meta.json" ] && [ -f "$src.old/tdq_meta.json" ]; then
    src=$src.old
fi
if [ ! -f "$src/tdq_meta.json" ]; then
    echo "no restorable hedge checkpoint found" >&2
    exit 1
fi
rm -rf runs/hedge_r5_ckpt
cp -r "$src" runs/hedge_r5_ckpt
echo "snapshot: $(du -sh runs/hedge_r5_ckpt | cut -f1) from $src"
