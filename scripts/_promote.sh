# Shared artifact-promotion gate for the TPU evidence scripts (sourced).
#
# promote <name> reads runs/<name>.new and moves it to BENCH_TPU_<name>.json
# ONLY when the last line is a real TPU measurement:
#   - non-empty file,
#   - no "backend_note" tag (cpu-fallback / total-failure sentinels),
#   - records "backend": "tpu" (every bench.py payload carries the backend
#     it actually ran on; jax can fall back to CPU without erroring).
# Anything else stays in runs/<name>.new for diagnosis and never clobbers a
# previously captured artifact.

# have_complete <name> — true when the canonical artifact exists AND is not
# a partial sweep.  Guards that used a bare [ -s ... ] would treat a promoted
# gap-filler partial as done forever and never re-attempt the complete run
# after the tunnel recovers (advisor finding, round 2).
have_complete() {
    [ -s "BENCH_TPU_$1.json" ] && ! grep -q '"partial"' "BENCH_TPU_$1.json"
}

promote() {
    local name="$1" new="runs/$1.new"
    [ -s "$new" ] || { echo "[$name] no output, NOT promoted"; return 1; }
    if grep -q '"backend_note"' "$new"; then
        echo "[$name] fallback/failure sentinel kept in $new, NOT promoted"
        return 1
    fi
    if ! grep -q '"backend": "tpu"' "$new"; then
        echo "[$name] backend is not tpu, kept in $new, NOT promoted"
        return 1
    fi
    if grep -q '"partial"' "$new" && [ -s "BENCH_TPU_$name.json" ] \
            && ! grep -q '"partial"' "BENCH_TPU_$name.json"; then
        echo "[$name] partial sweep kept in $new; complete artifact retained"
        return 1
    fi
    mv "$new" "BENCH_TPU_$name.json"
    tail -1 "BENCH_TPU_$name.json"
}
