"""Ablation: residual-importance resampling vs the reference's fixed draw.

Trains the same Burgers problem twice at the same budget — one fixed LHS
collocation set (the reference's only mode, ``domains.py:12-20``) and one
with ``resample_every`` redraws — and reports rel-L2 vs the Cole-Hopf
solution for each.  Writes runs/resample_ablation.json.

Usage:
  python scripts/resample_ablation.py              # TPU if reachable
  env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/resample_ablation.py
  ... --quick       tiny budget smoke run
"""
import argparse
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

import tensordiffeq_tpu as tdq
from tensordiffeq_tpu import (CollocationSolverND, DomainND, IC, dirichletBC,
                              grad)
from tensordiffeq_tpu.exact import burgers_solution


def build(n_f, seed=0):
    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], 256)
    domain.add("t", [0.0, 1.0], 100)
    domain.generate_collocation_points(n_f, seed=seed)
    bcs = [IC(domain, [lambda x: -np.sin(np.pi * x)], var=[["x"]]),
           dirichletBC(domain, val=0.0, var="x", target="upper"),
           dirichletBC(domain, val=0.0, var="x", target="lower")]

    def f_model(u, x, t):
        u_x = grad(u, "x")
        return (grad(u, "t")(x, t) + u(x, t) * u_x(x, t)
                - (0.01 / np.pi) * grad(u_x, "x")(x, t))

    return domain, bcs, f_model


def run(n_f, widths, adam, newton, resample_every, seed=0):
    domain, bcs, f_model = build(n_f, seed=seed)
    solver = CollocationSolverND(verbose=False)
    solver.compile([2, *widths, 1], f_model, domain, bcs)
    t0 = time.time()
    solver.fit(tf_iter=adam, newton_iter=newton,
               resample_every=resample_every, resample_seed=seed)
    wall = time.time() - t0
    x, t, usol = burgers_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = solver.predict(Xg, best_model=True)
    err = float(tdq.find_L2_error(u_pred, usol.reshape(-1, 1)))
    return {"seed": seed, "resample_every": resample_every, "rel_l2": err,
            "wall_s": round(wall, 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--seeds", type=int, default=1,
                    help="collocation-draw seeds per arm (advisor, round 2: "
                         "Burgers at this budget is high-variance — a "
                         "single-seed multiplier may not be robust)")
    args = ap.parse_args()

    if args.quick:
        n_f, widths, adam, newton, every = 1_000, [20, 20], 400, 200, 100
    else:
        n_f, widths, adam, newton, every = 5_000, [20] * 4, 3_000, 2_000, 500

    import jax
    out = {"backend": jax.default_backend(),
           "config": f"Burgers N_f={n_f}, 2-{'x'.join(map(str, widths))}-1, "
                     f"{adam} Adam + {newton} L-BFGS",
           "runs": []}
    improvements = []
    for seed in range(args.seeds):
        pair = {}
        for mode in (0, every):
            r = run(n_f, widths, adam, newton, mode, seed=seed)
            out["runs"].append(r)
            pair[mode] = r["rel_l2"]
            print(json.dumps(r), flush=True)
        if pair[every] > 0:
            improvements.append(pair[0] / pair[every])
    # single-seed key kept for compatibility with the round-2 artifact
    out["improvement"] = round(improvements[0], 2) if improvements else None
    if len(improvements) > 1:
        out["improvement_per_seed"] = [round(v, 2) for v in improvements]
        out["improvement_mean"] = round(float(np.mean(improvements)), 2)
        out["improvement_range"] = [round(min(improvements), 2),
                                    round(max(improvements), 2)]
        print(json.dumps({"improvement_mean": out["improvement_mean"],
                          "improvement_range": out["improvement_range"]}))
    else:
        print(json.dumps({"improvement_vs_fixed": out["improvement"]}))
    with open(os.path.join(ROOT, "runs", "resample_ablation.json"), "w") as fh:
        json.dump(out, fh, indent=1)


if __name__ == "__main__":
    main()
