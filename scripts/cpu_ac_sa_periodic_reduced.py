"""Reduced AC-SA with the exactly-periodic embedding net (beyond-reference)
vs the recorded plain-MLP SA arm — tunnel-independent evidence for the
`PeriodicMLP` ansatz on the flagship problem class.

Identical config/seed/budget to the plain reduced SA arm in
``runs/cpu_ac_sa_reduced.json`` (N_f=10k, 2-64x3-1, 10k Adam + 10k L-BFGS,
rel-L2 4.34e-2): the ONLY change is ``network=periodic_net(...)`` — the
x-periodicity the reference can only enforce softly (``boundaries.py:205``)
is built into the ansatz (exact to all derivative orders,
``networks.py::PeriodicMLP``).  The full-size on-chip comparison is the
watcher's extras step H; this is the CPU-feasible half.

Crash-safe: TDQ_CKPT-style resume via fit(checkpoint_dir=) — a session
boundary costs at most 500 epochs.

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    nice -n 15 python scripts/cpu_ac_sa_periodic_reduced.py
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "examples"))
sys.path.insert(0, ROOT)

N_F, NX, NT = 10_000, 512, 201
WIDTHS = [64, 64, 64]
ADAM, NEWTON = 10_000, 10_000
CKPT = os.path.join(ROOT, "runs", "ck_ac_sa_periodic_cpu")
OUT = os.path.join(ROOT, "runs", "cpu_ac_sa_periodic.json")


def main():
    from ac_baseline import build_problem

    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import CollocationSolverND
    from tensordiffeq_tpu.exact import allen_cahn_solution

    domain, bcs, f_model = build_problem(N_F, nx=NX, nt=NT)
    rng = np.random.RandomState(0)
    solver = CollocationSolverND(verbose=False)
    solver.compile(
        [2, *WIDTHS, 1], f_model, domain, bcs, Adaptive_type=1,
        dict_adaptive={"residual": [True], "BCs": [True, False]},
        init_weights={"residual": [rng.rand(N_F, 1)],
                      "BCs": [100.0 * rng.rand(NX, 1), None]},
        network=tdq.periodic_net([2, *WIDTHS, 1], domain, ["x"]))

    adam_done = newton_done = 0
    if os.path.exists(os.path.join(CKPT, "tdq_meta.json")):
        try:
            solver.restore_checkpoint(CKPT)
            newton_done = min(int(getattr(solver, "newton_done", 0)), NEWTON)
            adam_done = min(len(solver.losses) - newton_done, ADAM)
            print(f"[periodic] resumed: {adam_done} Adam, "
                  f"{newton_done} L-BFGS", flush=True)
        except Exception as e:
            print(f"[periodic] checkpoint not restorable ({e}); fresh",
                  flush=True)
    t0 = time.time()
    solver.fit(tf_iter=ADAM - adam_done, newton_iter=NEWTON - newton_done,
               checkpoint_dir=CKPT, checkpoint_every=500)
    wall = time.time() - t0

    x, t, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = solver.predict(Xg, best_model=True)
    err = float(tdq.find_L2_error(u_pred, usol.reshape(-1, 1)))
    out = {"arm": "periodic_net SA", "rel_l2": err,
           "wall_s_this_session": round(wall, 1),
           "config": f"N_f={N_F}, 2-64x3-1, {ADAM}+{NEWTON}, seed 0, "
                     "periodic_net(n_harmonics=4) — otherwise identical to "
                     "the plain-MLP SA arm (runs/cpu_ac_sa_reduced.json, "
                     "rel-L2 4.34e-2)"}
    with open(OUT + ".tmp", "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(OUT + ".tmp", OUT)
    print(json.dumps(out), flush=True)
    # completed: clear the resume point (fit_resumable convention)
    import shutil
    for d in (CKPT, CKPT + ".old", CKPT + ".tmp"):
        shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    main()
