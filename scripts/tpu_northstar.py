"""North-star completion: drive the flagship AC-SA config to the SA-PINN
accuracy bar (rel-L2 <= 2.1e-2, the paper number cited at reference
``models.py:37``) on the real TPU, and record the time it takes.

The 2026-08-01 parity capture (``BENCH_TPU_full.json``) ran the reference's
exact 10k Adam + 10k L-BFGS budget in 190 s but landed at rel-L2 9.3e-2:
the Adam curve was still dropping fast at cutoff (1.56e-1 -> 9.4e-2 over
the last 2k epochs) and the L-BFGS phase stopped silently within its first
chunks.  This driver answers both: it extends the Adam budget (at ~85
epochs/s the budget costs seconds, not hours), instruments the L-BFGS
phase (stop reasons now stream to stderr, ``training/lbfgs.py::_log_stop``),
and falls back across refinement flavors — zoom line search, the
reference's fixed-step rule (``optimizers.py:114``), generic-engine refine
loss — until the bar is reached or the time budget is spent.

Crash-safe and resumable (``runs/ns_ckpt`` + ``runs/ns_meta.json``): a
tunnel death mid-run costs one leg, not the run.  Productive time is
cumulative across windows, matching ``bench.bench_time_to_l2`` semantics.

The final payload goes to ``runs/northstar.new``; it is promoted to
``BENCH_TPU_northstar.json`` only when it ran on TPU (same gate as
``scripts/_promote.sh``).
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.chdir(REPO)

import numpy as np

SMOKE = os.environ.get("NS_SMOKE") == "1"  # tiny config, CPU allowed —
# tests the leg scheduler/resume/promotion logic without a tunnel window
# NS_ARM=periodic swaps the plain MLP for the exactly-periodic harmonic
# ansatz (networks.periodic_net, beyond-reference) at the SAME flagship
# config and chases the driver metric's literal bar, rel-L2 <= 1e-3
# (BASELINE.md north-star) — below what plain SA-PINN publishes (2.1e-2)
# but plausibly within the ansatz's reach: at one-fifth size on CPU it
# landed 7.7e-3 (`runs/cpu_ac_sa_periodic.json`).  Artifacts carry a
# `_periodic` suffix and promote to BENCH_TPU_northstar_periodic.json.
PERIODIC = os.environ.get("NS_ARM") == "periodic"
TARGET = float(os.environ.get(
    "NS_TARGET", 0.9 if SMOKE else (1e-3 if PERIODIC else 2.1e-2)))
ADAM_LEG = int(os.environ.get("NS_ADAM_LEG", 100 if SMOKE else 5_000))
ADAM_MAX = int(os.environ.get("NS_ADAM_MAX", 400 if SMOKE else 60_000))
NEWTON_LEG = int(os.environ.get("NS_NEWTON_LEG", 100 if SMOKE else 5_000))
BUDGET = float(os.environ.get("NS_BUDGET", 300 if SMOKE else 3_000))
if SMOKE:
    N_F, NX, NT = 2_048, 64, 16
    WIDTHS = [32, 32]
else:
    N_F, NX, NT = 50_000, 512, 201
    WIDTHS = [128, 128, 128, 128]
_SFX = ("_smoke" if SMOKE else "") + ("_periodic" if PERIODIC else "")
EVAL_EVERY = 50 if SMOKE else 1_000
CKPT = os.path.join(REPO, "runs", f"ns_ckpt{_SFX}")
META = os.path.join(REPO, "runs", f"ns_meta{_SFX}.json")
OUT_STREAM = os.path.join(REPO, "runs", f"northstar_stream{_SFX}.json")
OUT_NEW = os.path.join(REPO, "runs", f"northstar{_SFX}.new")
CANON = os.path.join(
    REPO, "BENCH_TPU_northstar_periodic.json" if PERIODIC
    else "BENCH_TPU_northstar.json")


def build_periodic_solver():
    """The flagship AC-SA config with the exactly-periodic ansatz, via
    the ONE shared builder (`examples/ac_baseline.py::build_sa_solver` —
    reference `AC-SA.py:12,55-56,64` + `periodic_net`).  Embedding nets
    bypass the MLP-only fused engine, so this runs the generic autodiff
    engine — fine on-chip (`BENCH_TPU_engines.json`: generic within 4%
    of pallas at f32)."""
    sys.path.insert(0, os.path.join(REPO, "examples"))
    from ac_baseline import build_sa_solver

    return (build_sa_solver(N_F, NX, NT, WIDTHS, periodic=True),
            "generic+periodic_net")


def log(msg):
    print(f"[ns] {msg}", file=sys.stderr, flush=True)


def main():
    import jax
    if jax.devices()[0].platform == "cpu" and not SMOKE:
        log("backend is CPU — refusing to burn the flagship run off-chip")
        return 3

    import bench
    from tensordiffeq_tpu.exact import allen_cahn_solution
    from tensordiffeq_tpu.helpers import find_L2_error

    xg, tg, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(xg, tg, indexing="ij"), -1).reshape(-1, 2)
    u_star = usol.reshape(-1, 1)

    if PERIODIC:
        solver, engine_used = build_periodic_solver()
    else:
        solver, engine_used = bench.build_solver_fallback(
            N_F, NX, NT, WIDTHS, bench.engine_hint(), "ns", grad_probe=True)

    meta = {"adam_done": 0, "newton_done": 0, "t_prev": 0.0, "windows": 0,
            "timeline": [], "t_target": None, "legs": []}
    if os.path.exists(os.path.join(CKPT, "tdq_meta.json")) \
            and os.path.exists(META):
        try:
            solver.restore_checkpoint(CKPT)
            with open(META) as fh:
                meta = json.load(fh)
            # the checkpoint is newer than the meta when the trainer died
            # MID-leg (fit checkpoints every 1000 epochs; meta's counters
            # only advance when a leg completes) — trust the solver state:
            # len(solver.losses) counts every Adam epoch + L-BFGS iter that
            # actually ran (fit docstring contract), newton_done the L-BFGS
            # share.  Without this a resume would replay the mid-leg epochs
            # while reporting them only once.
            # Adam-phase checkpoints store newton_done=0 even when prior
            # L-BFGS legs ran (collocation.py:1161-1164), so take the
            # L-BFGS share from whichever source knows more BEFORE
            # splitting losses into phases — else prior L-BFGS iters get
            # counted as Adam epochs and later newton legs lose credit
            ck_newton = max(int(getattr(solver, "newton_done", 0)),
                            int(meta["newton_done"]))
            ck_adam = max(len(solver.losses) - ck_newton, 0)
            meta["newton_done"] = ck_newton
            meta["adam_done"] = max(meta["adam_done"], ck_adam)
            solver.newton_done = ck_newton  # fit's newton_prior: absolute
            log(f"resumed: {meta['adam_done']} Adam, {meta['newton_done']} "
                f"L-BFGS, {meta['t_prev']:.0f}s productive, "
                f"window #{meta['windows'] + 1}")
        except Exception as e:
            log(f"checkpoint not restorable ({type(e).__name__}: {e}); fresh")
    meta["windows"] += 1
    t0 = time.time()
    Xg_j = None

    # telemetry subscription (PR 4): the run's config, sampled per-epoch
    # losses/grad-norm, step-time split, λ stats, and any divergence land
    # in runs/ns_telemetry*/events.jsonl — structured, resumable-appendable
    # — instead of being scraped off this script's stderr.  Metrics-only
    # raise policy: a NaN must surface through the artifact/report, not
    # kill a tunnel window mid-leg.
    import atexit

    from tensordiffeq_tpu import telemetry as tdq_telemetry
    ns_run = tdq_telemetry.RunLogger(
        os.path.join(REPO, "runs", f"ns_telemetry{_SFX}"),
        config={"n_f": N_F, "widths": WIDTHS, "periodic": PERIODIC,
                "target": TARGET, "window": meta["windows"]})
    atexit.register(ns_run.close)
    ns_tele = tdq_telemetry.TrainingTelemetry(
        logger=ns_run, log_every=EVAL_EVERY, raise_on_divergence=False,
        grad_norm=False)  # the run IS the headline measurement: keep the
    # compiled step bit-identical to pre-telemetry captures (no per-step
    # global-norm reduction skewing t_target)

    def now():
        # CUMULATIVE productive time across windows — reporting only
        # (timelines, t_target, persisted meta); never a budget gate
        return meta["t_prev"] + time.time() - t0

    def window_elapsed():
        # THIS window's productive share — the quantity NS_BUDGET caps
        # (per-window yield, tpu_convergence_extra.sh:41): a window that
        # spends its share exits "partial" and the next window resumes
        # with its own full share
        return time.time() - t0

    def eval_l2(params=None):
        nonlocal Xg_j
        import jax.numpy as jnp
        if Xg_j is None:
            Xg_j = jnp.asarray(Xg, jnp.float32)
        p = solver.params if params is None else params
        u_pred = np.asarray(solver._apply_jit(p, Xg_j))
        return float(find_L2_error(u_pred, u_star))

    def record(phase, abs_step, l2):
        t = round(now(), 1)
        meta["timeline"].append({"t": t, "phase": f"{phase}@{abs_step}",
                                 "l2": l2})
        if meta["t_target"] is None and l2 <= TARGET:
            meta["t_target"] = t
        log(f"t={t:7.1f}s {phase}@{abs_step}: rel-L2={l2:.3e}")

    def persist(status):
        meta_out = dict(meta, t_prev=round(now(), 1))
        with open(META + ".tmp", "w") as fh:
            json.dump(meta_out, fh)
        os.replace(META + ".tmp", META)
        payload = {
            "metric": (f"AC-SA{'+periodic_net' if PERIODIC else ''} "
                       f"time-to-rel-L2<={TARGET:g} (north star)"),
            "value": meta["t_target"], "unit": "s",
            "vs_baseline": meta["timeline"][-1]["l2"] if meta["timeline"]
            else None,
            "target": TARGET, "engine": engine_used,
            "adam_done": meta["adam_done"], "newton_done": meta["newton_done"],
            "windows": meta["windows"], "status": status,
            "legs": meta["legs"], "timeline": meta["timeline"],
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "captured": time.strftime("%Y-%m-%d"),
        }
        with open(OUT_STREAM + ".tmp", "w") as fh:
            json.dump(payload, fh, indent=1)
        os.replace(OUT_STREAM + ".tmp", OUT_STREAM)
        return payload

    def run_adam(n):
        a0 = meta["adam_done"]

        def eval_fn(phase, step, params):
            record("adam", a0 + step, eval_l2(params))
            persist("partial")

        solver.fit(tf_iter=n, eval_fn=eval_fn, eval_every=EVAL_EVERY,
                   checkpoint_dir=CKPT, checkpoint_every=EVAL_EVERY,
                   telemetry=ns_tele)
        meta["adam_done"] = a0 + n
        meta["legs"].append({"kind": "adam", "n": n, "t": round(now(), 1)})

    def run_newton(n, eager=None, label="zoom"):
        n0 = meta["newton_done"]

        def eval_fn(phase, step, params):
            record(f"l-bfgs[{label}]", n0 + step, eval_l2(params))
            persist("partial")

        before = eval_l2()
        solver.fit(newton_iter=n, newton_eager=eager,
                   eval_fn=eval_fn, eval_every=EVAL_EVERY,
                   checkpoint_dir=CKPT, checkpoint_every=EVAL_EVERY,
                   telemetry=ns_tele)
        # how far did it actually get?  fit credits actual iterations
        ran = solver.newton_done - n0 if hasattr(solver, "newton_done") else n
        meta["newton_done"] = n0 + max(int(ran), 0)
        after = eval_l2()
        record(f"l-bfgs[{label}]", meta["newton_done"], after)
        meta["legs"].append({"kind": f"l-bfgs[{label}]", "n": int(ran),
                             "l2_before": before, "l2_after": after,
                             "t": round(now(), 1)})
        persist("partial")
        return before, after, int(ran)

    # ---- schedule ----------------------------------------------------- #
    # 1) make sure at least the reference Adam budget has run (capped by
    # ADAM_MAX so a smoke/bounded run respects its ceiling)
    first = min(10_000, ADAM_MAX)
    if meta["adam_done"] < first:
        run_adam(first - meta["adam_done"])
        record("adam", meta["adam_done"], eval_l2())
        persist("partial")

    def switch_to_generic_refine():
        """Swap the L-BFGS loss to the generic autodiff engine — the
        diagnosis lever for a refinement stall that is the fused/pallas
        engine's fault rather than L-BFGS's (the generic engine is the
        autotune cross-check oracle, so its gradients are the trusted
        ones)."""
        solver._refine_residual = None
        solver._assemble_losses()
        log("refine loss switched to the generic autodiff engine")

    # Schedule (revised after the 2026-08-01 live run): a refinement
    # flavor that is PAYING is repeated until it stops paying, and Adam
    # only runs when no refinement flavor progresses.  The first version
    # tried each flavor once and then returned to Adam every round — on
    # the live window the eager leg took rel-L2 9.35e-2 -> 3.73e-2 (still
    # descending) and the follow-up Adam leg promptly UNDID it (5.9e-2):
    # an Adam step at lr 5e-3 from an L-BFGS iterate walks off the
    # refined minimum.  "Paying" = >=5% relative L2 drop over the leg
    # (the stall predicate's complement: both 2026-08-01 full-size zoom
    # runs froze rel-L2 to 4 digits, a degenerate-step signature).
    # the periodic arm's refine loss IS the generic engine already — the
    # diagnostic switch would re-run an identical just-dried leg
    tried_generic = PERIODIC \
        or any("generic" in l["kind"] for l in meta["legs"])
    # the generic-engine switch is PERMANENT in-process (every leg after
    # it runs the generic refine loss, paying or not) — a faithful resume
    # re-applies it whenever any generic leg exists in history, not just
    # when the most recent leg paid
    generic_on = tried_generic and not PERIODIC
    if generic_on:
        switch_to_generic_refine()
    working = None  # refinement flavor currently paying, from legs history
    for l in reversed(meta["legs"]):
        if l["kind"].startswith("l-bfgs") and "l2_before" in l:
            if l["l2_after"] < 0.95 * l["l2_before"]:
                working = ("eager" if "eager" in l["kind"] else "zoom")
            break

    def paying(before, after):
        return (before - after) >= 0.05 * before

    def leg_label(flavor):
        return f"{flavor}-generic" if generic_on else flavor

    last_dried = None  # flavor that just stopped paying — skip its
    # immediate retry in the fresh round that follows
    # the cumulative backstop also gates the LOOP: a window resuming with
    # now() already past it must fall straight through to the terminal
    # status, not burn a full share first
    total_budget = float(os.environ.get("NS_TOTAL_BUDGET", 10 * BUDGET))
    while window_elapsed() < BUDGET and now() < total_budget \
            and meta["adam_done"] <= ADAM_MAX:
        l2 = eval_l2()
        if l2 <= TARGET:
            break
        progressed = False
        if working is not None:
            # keep riding the proven flavor until it stops paying
            before, after, ran = run_newton(
                NEWTON_LEG, eager=(True if working == "eager" else None),
                label=leg_label(working))
            if after <= TARGET:
                break
            progressed = paying(before, after)
            if not progressed:
                last_dried = working
                working = None
                # go straight to a fresh refinement round with the OTHER
                # flavors — an Adam leg at lr 5e-3 from an L-BFGS iterate
                # regresses L2 (measured: 3.73e-2 -> 5.9e-2), so Adam is
                # the last resort, not the dry-flavor reflex
                continue
        else:
            # fresh refinement round: zoom line search, then the
            # reference-parity fixed-step rule, then (once) the
            # generic-engine refine loss as the engine-fault diagnostic
            for flavor, eager in (("zoom", None), ("eager", True)):
                if flavor == last_dried or window_elapsed() >= BUDGET:
                    continue
                before, after, ran = run_newton(NEWTON_LEG, eager=eager,
                                                label=leg_label(flavor))
                if after <= TARGET or paying(before, after):
                    working = flavor
                    progressed = True
                    break
            if working is None and not tried_generic \
                    and window_elapsed() < BUDGET:
                tried_generic = True
                switch_to_generic_refine()
                generic_on = True
                before, after, ran = run_newton(NEWTON_LEG, eager=None,
                                                label="zoom-generic")
                if after <= TARGET or paying(before, after):
                    working = "zoom"
                    progressed = True
            last_dried = None
            if working is not None and after <= TARGET:
                break
        if progressed:
            continue
        if window_elapsed() >= BUDGET:
            break
        # no refinement flavor is paying: more Adam — measured to still
        # be improving fast at 10k; clipped so the env cap is a ceiling
        leg = min(ADAM_LEG, ADAM_MAX - meta["adam_done"])
        if leg <= 0:
            break
        run_adam(leg)
        record("adam", meta["adam_done"], eval_l2())
        persist("partial")

    final_l2 = eval_l2()
    # final timeline point — also sets t_target when a restored checkpoint
    # already beat the bar before any in-loop record() fired
    record("final", meta["adam_done"] + meta["newton_done"], final_l2)
    done = final_l2 <= TARGET
    # "exhausted" is TERMINAL: the Adam ceiling was spent without reaching
    # the bar — without it the watcher/extras queue would re-launch a
    # flagship compile plus a 5000-iter refinement leg on every healthy
    # probe forever.  NS_BUDGET is a PER-WINDOW cap (window_elapsed above;
    # tpu_convergence_extra.sh:41): a window that merely spent its share
    # exits "partial" and the next window resumes toward the ceiling —
    # cumulative now() never gates a window's work.  But adam_done only
    # advances when NO refinement flavor pays, so a Newton chase that
    # keeps paying 5% per leg while asymptoting above TARGET would never
    # hit the Adam ceiling: NS_TOTAL_BUDGET (cumulative productive time,
    # default 10 windows' worth) is the terminal backstop for that path.
    # (A window death mid-leg never lands here either: the killed process
    # writes no final status, the streamed meta stays "partial", and the
    # next window resumes.)
    if done:
        status = "complete"
    elif meta["adam_done"] >= ADAM_MAX or now() >= total_budget:
        status = "exhausted"
    else:
        status = "partial"
    payload = persist(status)
    with open(OUT_NEW, "w") as fh:
        json.dump(payload, fh, indent=1)
        fh.write("\n")
    log(f"final rel-L2={final_l2:.3e} after {meta['adam_done']} Adam + "
        f"{meta['newton_done']} L-BFGS, {now():.0f}s productive, "
        f"t_target={meta['t_target']}, status={status}")
    # promote (same gate as scripts/_promote.sh): real TPU payloads only —
    # and never from a smoke run, whose toy config would close the
    # watcher's north-star gate with a meaningless 'complete'.  A terminal
    # artifact (complete/exhausted) is never clobbered by a partial one.
    if payload["backend"] == "tpu" and not SMOKE:
        canon_terminal = False
        if os.path.exists(CANON):
            try:
                with open(CANON) as fh:
                    canon_terminal = json.load(fh).get("status") in (
                        "complete", "exhausted")
            except Exception:
                pass
        if status in ("complete", "exhausted") or not canon_terminal:
            os.replace(OUT_NEW, CANON)
            log(f"promoted -> {CANON}")
    if done:
        import shutil
        for d in (CKPT, CKPT + ".old", CKPT + ".tmp"):
            shutil.rmtree(d, ignore_errors=True)
    print(json.dumps({k: v for k, v in payload.items() if k != "timeline"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
