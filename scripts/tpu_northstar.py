"""North-star completion: drive the flagship AC-SA config to the SA-PINN
accuracy bar (rel-L2 <= 2.1e-2, the paper number cited at reference
``models.py:37``) on the real TPU, and record the time it takes.

The 2026-08-01 parity capture (``BENCH_TPU_full.json``) ran the reference's
exact 10k Adam + 10k L-BFGS budget in 190 s but landed at rel-L2 9.3e-2:
the Adam curve was still dropping fast at cutoff (1.56e-1 -> 9.4e-2 over
the last 2k epochs) and the L-BFGS phase stopped silently within its first
chunks.  This driver answers both: it extends the Adam budget (at ~85
epochs/s the budget costs seconds, not hours), instruments the L-BFGS
phase (stop reasons now stream to stderr, ``training/lbfgs.py::_log_stop``),
and falls back across refinement flavors — zoom line search, the
reference's fixed-step rule (``optimizers.py:114``), generic-engine refine
loss — until the bar is reached or the time budget is spent.

Crash-safe and resumable (``runs/ns_ckpt`` + ``runs/ns_meta.json``): a
tunnel death mid-run costs one leg, not the run.  Productive time is
cumulative across windows, matching ``bench.bench_time_to_l2`` semantics.

The final payload goes to ``runs/northstar.new``; it is promoted to
``BENCH_TPU_northstar.json`` only when it ran on TPU (same gate as
``scripts/_promote.sh``).
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.chdir(REPO)

import numpy as np

TARGET = 2.1e-2
ADAM_LEG = int(os.environ.get("NS_ADAM_LEG", 5_000))
ADAM_MAX = int(os.environ.get("NS_ADAM_MAX", 60_000))
NEWTON_LEG = int(os.environ.get("NS_NEWTON_LEG", 5_000))
BUDGET = float(os.environ.get("NS_BUDGET", 3_000))  # productive seconds
N_F, NX, NT = 50_000, 512, 201
WIDTHS = [128, 128, 128, 128]
CKPT = os.path.join(REPO, "runs", "ns_ckpt")
META = os.path.join(REPO, "runs", "ns_meta.json")
OUT_STREAM = os.path.join(REPO, "runs", "northstar_stream.json")
OUT_NEW = os.path.join(REPO, "runs", "northstar.new")
CANON = os.path.join(REPO, "BENCH_TPU_northstar.json")


def log(msg):
    print(f"[ns] {msg}", file=sys.stderr, flush=True)


def main():
    import jax
    if jax.devices()[0].platform == "cpu":
        log("backend is CPU — refusing to burn the flagship run off-chip")
        return 3

    import bench
    from tensordiffeq_tpu.exact import allen_cahn_solution
    from tensordiffeq_tpu.helpers import find_L2_error

    xg, tg, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(xg, tg, indexing="ij"), -1).reshape(-1, 2)
    u_star = usol.reshape(-1, 1)

    solver, engine_used = bench.build_solver_fallback(
        N_F, NX, NT, WIDTHS, bench.engine_hint(), "ns", grad_probe=True)

    meta = {"adam_done": 0, "newton_done": 0, "t_prev": 0.0, "windows": 0,
            "timeline": [], "t_target": None, "legs": []}
    if os.path.exists(os.path.join(CKPT, "tdq_meta.json")) \
            and os.path.exists(META):
        try:
            solver.restore_checkpoint(CKPT)
            with open(META) as fh:
                meta = json.load(fh)
            # the checkpoint is newer than the meta when the trainer died
            # MID-leg (fit checkpoints every 1000 epochs; meta's counters
            # only advance when a leg completes) — trust the solver state:
            # len(solver.losses) counts every Adam epoch + L-BFGS iter that
            # actually ran (fit docstring contract), newton_done the L-BFGS
            # share.  Without this a resume would replay the mid-leg epochs
            # while reporting them only once.
            ck_newton = int(getattr(solver, "newton_done", 0))
            ck_adam = max(len(solver.losses) - ck_newton, 0)
            meta["newton_done"] = max(meta["newton_done"], ck_newton)
            meta["adam_done"] = max(meta["adam_done"], ck_adam)
            log(f"resumed: {meta['adam_done']} Adam, {meta['newton_done']} "
                f"L-BFGS, {meta['t_prev']:.0f}s productive, "
                f"window #{meta['windows'] + 1}")
        except Exception as e:
            log(f"checkpoint not restorable ({type(e).__name__}: {e}); fresh")
    meta["windows"] += 1
    t0 = time.time()
    Xg_j = None

    def now():
        return meta["t_prev"] + time.time() - t0

    def eval_l2(params=None):
        nonlocal Xg_j
        import jax.numpy as jnp
        if Xg_j is None:
            Xg_j = jnp.asarray(Xg, jnp.float32)
        p = solver.params if params is None else params
        u_pred = np.asarray(solver._apply_jit(p, Xg_j))
        return float(find_L2_error(u_pred, u_star))

    def record(phase, abs_step, l2):
        t = round(now(), 1)
        meta["timeline"].append({"t": t, "phase": f"{phase}@{abs_step}",
                                 "l2": l2})
        if meta["t_target"] is None and l2 <= TARGET:
            meta["t_target"] = t
        log(f"t={t:7.1f}s {phase}@{abs_step}: rel-L2={l2:.3e}")

    def persist(status):
        meta_out = dict(meta, t_prev=round(now(), 1))
        with open(META + ".tmp", "w") as fh:
            json.dump(meta_out, fh)
        os.replace(META + ".tmp", META)
        payload = {
            "metric": "AC-SA time-to-rel-L2<=2.1e-2 (north star)",
            "value": meta["t_target"], "unit": "s",
            "vs_baseline": meta["timeline"][-1]["l2"] if meta["timeline"]
            else None,
            "target": TARGET, "engine": engine_used,
            "adam_done": meta["adam_done"], "newton_done": meta["newton_done"],
            "windows": meta["windows"], "status": status,
            "legs": meta["legs"], "timeline": meta["timeline"],
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "captured": time.strftime("%Y-%m-%d"),
        }
        with open(OUT_STREAM + ".tmp", "w") as fh:
            json.dump(payload, fh, indent=1)
        os.replace(OUT_STREAM + ".tmp", OUT_STREAM)
        return payload

    def run_adam(n):
        a0 = meta["adam_done"]

        def eval_fn(phase, step, params):
            record("adam", a0 + step, eval_l2(params))
            persist("partial")

        solver.fit(tf_iter=n, eval_fn=eval_fn, eval_every=1_000,
                   checkpoint_dir=CKPT, checkpoint_every=1_000)
        meta["adam_done"] = a0 + n
        meta["legs"].append({"kind": "adam", "n": n, "t": round(now(), 1)})

    def run_newton(n, eager=None, label="zoom"):
        n0 = meta["newton_done"]

        def eval_fn(phase, step, params):
            record(f"l-bfgs[{label}]", n0 + step, eval_l2(params))
            persist("partial")

        before = eval_l2()
        solver.fit(newton_iter=n, newton_eager=eager,
                   eval_fn=eval_fn, eval_every=1_000,
                   checkpoint_dir=CKPT, checkpoint_every=1_000)
        # how far did it actually get?  fit credits actual iterations
        ran = solver.newton_done - n0 if hasattr(solver, "newton_done") else n
        meta["newton_done"] = n0 + max(int(ran), 0)
        after = eval_l2()
        record(f"l-bfgs[{label}]", meta["newton_done"], after)
        meta["legs"].append({"kind": f"l-bfgs[{label}]", "n": int(ran),
                             "l2_before": before, "l2_after": after,
                             "t": round(now(), 1)})
        persist("partial")
        return before, after, int(ran)

    # ---- schedule ----------------------------------------------------- #
    # 1) make sure at least the reference Adam budget has run
    if meta["adam_done"] < 10_000:
        run_adam(10_000 - meta["adam_done"])
        record("adam", meta["adam_done"], eval_l2())
        persist("partial")

    tried_eager = any(l["kind"] == "l-bfgs[eager]" for l in meta["legs"])
    while now() < BUDGET and meta["adam_done"] <= ADAM_MAX:
        l2 = eval_l2()
        if l2 <= TARGET:
            break
        # 2) refinement attempt: zoom line search first
        before, after, ran = run_newton(NEWTON_LEG, eager=None, label="zoom")
        if after <= TARGET:
            break
        stalled = ran < NEWTON_LEG // 2 and (before - after) < 0.1 * before
        if stalled and not tried_eager and now() < BUDGET:
            # 3) reference-parity fixed-step rule as fallback
            tried_eager = True
            before, after, ran = run_newton(NEWTON_LEG, eager=True,
                                            label="eager")
            if after <= TARGET:
                break
        if now() >= BUDGET:
            break
        # 4) more Adam — measured to still be improving fast at 10k;
        # the leg is clipped so the env-var cap is a true ceiling
        leg = min(ADAM_LEG, ADAM_MAX - meta["adam_done"])
        if leg <= 0:
            break
        run_adam(leg)
        record("adam", meta["adam_done"], eval_l2())
        persist("partial")

    final_l2 = eval_l2()
    # final timeline point — also sets t_target when a restored checkpoint
    # already beat the bar before any in-loop record() fired
    record("final", meta["adam_done"] + meta["newton_done"], final_l2)
    done = final_l2 <= TARGET
    status = "complete" if done else "partial"
    payload = persist(status)
    with open(OUT_NEW, "w") as fh:
        json.dump(payload, fh, indent=1)
        fh.write("\n")
    log(f"final rel-L2={final_l2:.3e} after {meta['adam_done']} Adam + "
        f"{meta['newton_done']} L-BFGS, {now():.0f}s productive, "
        f"t_target={meta['t_target']}")
    # promote (same gate as scripts/_promote.sh): real TPU payloads only;
    # a complete artifact is never clobbered by a partial one
    if payload["backend"] == "tpu":
        canon_complete = False
        if os.path.exists(CANON):
            try:
                with open(CANON) as fh:
                    canon_complete = json.load(fh).get("status") == "complete"
            except Exception:
                pass
        if done or not canon_complete:
            os.replace(OUT_NEW, CANON)
            log(f"promoted -> {CANON}")
    if done:
        import shutil
        for d in (CKPT, CKPT + ".old", CKPT + ".tmp"):
            shutil.rmtree(d, ignore_errors=True)
    print(json.dumps({k: v for k, v in payload.items() if k != "timeline"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
