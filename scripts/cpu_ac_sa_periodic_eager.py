"""Reduced periodic-ansatz AC-SA, eager-refinement arm.

The recorded reduced periodic arm (``runs/cpu_ac_sa_periodic.json``,
rel-L2 7.73e-3) ran the default zoom line search in its L-BFGS phase.
The on-chip north-star diagnosis (2026-08-01) showed zoom degenerating at
SA scale while the reference-parity fixed-step eager rule keeps paying —
this arm measures that flavor difference at the reduced size: identical
config/seed/budget to the recorded arm, ONLY change ``newton_eager=True``.
Outcome either de-risks the extras-H full-size rel-L2<=1e-3 chase (eager
meaningfully below 7.73e-3 here) or shows the reduced config's ansatz
floor is flavor-independent.

Crash-safe resume via fit(checkpoint_dir=).

Usage: env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    nice -n 19 python scripts/cpu_ac_sa_periodic_eager.py
"""
import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "examples"))
sys.path.insert(0, ROOT)

N_F, NX, NT = 10_000, 512, 201
WIDTHS = [64, 64, 64]
ADAM, NEWTON = 10_000, 10_000
CKPT = os.path.join(ROOT, "runs", "ck_ac_sa_periodic_eager_cpu")
OUT = os.path.join(ROOT, "runs", "cpu_ac_sa_periodic_eager.json")


def main():
    from ac_baseline import build_sa_solver

    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu.exact import allen_cahn_solution

    solver = build_sa_solver(N_F, NX, NT, WIDTHS, periodic=True)

    adam_done = newton_done = 0
    if os.path.exists(os.path.join(CKPT, "tdq_meta.json")):
        try:
            solver.restore_checkpoint(CKPT)
            newton_done = min(int(getattr(solver, "newton_done", 0)), NEWTON)
            adam_done = min(len(solver.losses) - newton_done, ADAM)
            print(f"[periodic-eager] resumed: {adam_done} Adam, "
                  f"{newton_done} L-BFGS", flush=True)
        except Exception as e:
            print(f"[periodic-eager] checkpoint not restorable ({e}); fresh",
                  flush=True)
    t0 = time.time()
    solver.fit(tf_iter=ADAM - adam_done, newton_iter=NEWTON - newton_done,
               newton_eager=True, checkpoint_dir=CKPT, checkpoint_every=500)
    wall = time.time() - t0

    x, t, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(x, t, indexing="ij"), -1).reshape(-1, 2)
    u_pred, _ = solver.predict(Xg, best_model=True)
    err = float(tdq.find_L2_error(u_pred, usol.reshape(-1, 1)))
    out = {"arm": "periodic_net SA, eager L-BFGS", "rel_l2": err,
           "wall_s_this_session": round(wall, 1),
           "config": f"N_f={N_F}, 2-64x3-1, {ADAM}+{NEWTON}, seed 0, "
                     "newton_eager=True — otherwise identical to the "
                     "recorded zoom arm (runs/cpu_ac_sa_periodic.json, "
                     "rel-L2 7.73e-3)"}
    with open(OUT + ".tmp", "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(OUT + ".tmp", OUT)
    print(json.dumps(out), flush=True)
    import shutil
    for d in (CKPT, CKPT + ".old", CKPT + ".tmp"):
        shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    main()
