#!/bin/bash
# Additional full-size convergence runs on the real TPU, after
# scripts/tpu_evidence.sh (which covers AC-SA).  Each run is the full
# reference config; rel-L2 / recovered coefficients land in runs/*.log
# and are transcribed into CONVERGENCE.md.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs

echo "=== A. Allen-Cahn baseline (N_f=50k, 10k Adam + 10k L-BFGS) ==="
timeout 5400 python examples/ac_baseline.py > runs/ac_baseline_full_tpu.log 2>&1
grep "Error u" runs/ac_baseline_full_tpu.log || tail -3 runs/ac_baseline_full_tpu.log

echo "=== B. Burgers forward (N_f=10k, 10k Adam + 10k L-BFGS) ==="
timeout 5400 python examples/burgers.py > runs/burgers_full_tpu.log 2>&1
grep "Error u" runs/burgers_full_tpu.log || tail -3 runs/burgers_full_tpu.log

echo "=== C. Allen-Cahn discovery (512x201 grid, SA, 10k Adam, ckpt+resume) ==="
timeout 5400 python examples/ac_discovery.py > runs/ac_discovery_full_tpu.log 2>&1
grep "c1 = " runs/ac_discovery_full_tpu.log || tail -3 runs/ac_discovery_full_tpu.log

echo "=== D. single-chip N_f scaling sweep (50k..500k) ==="
timeout 3000 python bench.py --scale > BENCH_TPU_scale.json 2> runs/bench_scale_tpu.log
tail -1 BENCH_TPU_scale.json

echo "ALL EXTRA CONVERGENCE RUNS DONE"
