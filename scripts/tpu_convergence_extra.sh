#!/bin/bash
# Additional full-size convergence runs on the real TPU, after
# scripts/tpu_evidence.sh (which covers AC-SA).  Each run is the full
# reference config; rel-L2 / recovered coefficients land in runs/*.log
# and are transcribed into CONVERGENCE.md.
#
# Steps are idempotent (skipped once their success marker exists) and each
# is gated on a fresh tunnel-health probe: if the tunnel died mid-suite the
# examples would pin CPU (examples/_common.py::resolve_backend) and churn
# for hours at full size — skip instead, a later watcher pass retries.
set -u
cd "$(dirname "$0")/.."
mkdir -p runs
. scripts/_promote.sh

# see tpu_evidence.sh: never burn the tunnel window on unpromotable
# CPU fallbacks from the watcher
export BENCH_NO_CPU_FALLBACK=1
# every full-size run checkpoints mid-run (fit_resumable/TDQ_CKPT):
# a tunnel death at minute 80 of an 85-minute config resumes on the
# next watcher pass instead of restarting from zero

healthy() {
    # resolve_backend cache lives in tempfile.gettempdir() (honours TMPDIR,
    # examples/_common.py) — clear it so a stale cpu pin can't survive
    rm -f "${TMPDIR:-/tmp}/tdq_backend_probe.json"
    timeout 120 python -c "
import jax
assert jax.devices()[0].platform != 'cpu'
" 2>/dev/null
}

done_marker() {  # done_marker <file> <pattern>
    [ -s "$1" ] && grep -aq "$2" "$1"
}

echo "=== 0. North-star: AC-SA to the 2.1e-2 SA-PINN bar (time-to-L2) ==="
# FIRST in the extras (the single number the project exists to produce;
# VERDICT r4 #1): extend past the reference budget until the paper bar is
# reached, with instrumented L-BFGS fallbacks.  Resumable across windows
# (runs/ns_ckpt); NS_BUDGET caps one window's productive share so the
# smaller extras below still get tunnel time.  Self-promotes to
# BENCH_TPU_northstar.json (TPU payloads only).
if [ -s BENCH_TPU_northstar.json ] \
        && grep -qE '"status": "(complete|exhausted)"' BENCH_TPU_northstar.json; then
    echo "done already (terminal)"
elif healthy; then
    NS_BUDGET=2000 timeout 2600 python scripts/tpu_northstar.py \
        >> runs/northstar_tpu.log 2>&1
    tail -2 runs/northstar_tpu.log
else echo "SKIP: tunnel unhealthy"; fi

echo "=== A. Allen-Cahn baseline (N_f=50k, 10k Adam + 10k L-BFGS) ==="
if done_marker runs/ac_baseline_full_tpu.log "Error u"; then echo "done already"
elif healthy; then
    TDQ_CKPT=runs/ck_ac_baseline timeout 5400 python examples/ac_baseline.py > runs/ac_baseline_full_tpu.log 2>&1
    grep -a "Error u" runs/ac_baseline_full_tpu.log || tail -3 runs/ac_baseline_full_tpu.log
else echo "SKIP: tunnel unhealthy"; fi

echo "=== B. Burgers forward (N_f=10k, 10k Adam + 10k L-BFGS) ==="
if done_marker runs/burgers_full_tpu.log "Error u"; then echo "done already"
elif healthy; then
    TDQ_CKPT=runs/ck_burgers timeout 5400 python examples/burgers.py > runs/burgers_full_tpu.log 2>&1
    grep -a "Error u" runs/burgers_full_tpu.log || tail -3 runs/burgers_full_tpu.log
else echo "SKIP: tunnel unhealthy"; fi

echo "=== C. Allen-Cahn discovery (512x201 grid, 12k Adam, per-var lr) ==="
# Config evidence (512x26 CPU runs, 2026-07-31): per-var rates 2e-5/0.01
# are required (a shared rate parks c1 at an Adam noise floor 10x its 1e-4
# target), and the unbounded SA λ ascent degrades the u-fit over long runs
# and drains c2 (SA: c2 4.91→4.03, loss 2.3e-4→7.3e-3; no-SA: c2=5.0000
# exactly at 6k with loss still falling).  The headline run is therefore
# no-SA; the reference-example SA config is captured separately below.
# artifact names carry the config token (nosa12k): a log completed under
# an earlier config can never satisfy this config's done-marker, and the
# filename alone says which config produced it (ADVICE r3)
if done_marker runs/ac_discovery_full_nosa12k_tpu.log "c1 = " \
        && [ -s runs/ac_discovery_full_nosa12k_tpu.json ]; then echo "done already"
elif healthy; then
    timeout 5400 python examples/ac_discovery.py \
        --no-sa --iters 12000 --lr_vars 2e-5,0.01 \
        --out runs/ac_discovery_full_nosa12k_tpu.json \
        > runs/ac_discovery_full_nosa12k_tpu.log 2>&1
    grep -a "c1 = " runs/ac_discovery_full_nosa12k_tpu.log || tail -3 runs/ac_discovery_full_nosa12k_tpu.log
else echo "SKIP: tunnel unhealthy"; fi

echo "=== C2. Allen-Cahn discovery, SA parity config (reference example) ==="
# the reference's own AC-discovery.py uses SA col_weights at 10k iters;
# capture it at exactly that budget for the parity record
if done_marker runs/ac_discovery_sa10k_tpu.log "c1 = " \
        && [ -s runs/ac_discovery_sa10k_tpu.json ]; then echo "done already"
elif healthy; then
    timeout 5400 python examples/ac_discovery.py \
        --iters 10000 --lr_vars 2e-5,0.01 \
        --out runs/ac_discovery_sa10k_tpu.json \
        > runs/ac_discovery_sa10k_tpu.log 2>&1
    grep -a "c1 = " runs/ac_discovery_sa10k_tpu.log || tail -3 runs/ac_discovery_sa10k_tpu.log
else echo "SKIP: tunnel unhealthy"; fi

echo "=== D. single-chip N_f scaling sweep (50k..500k) ==="
# have_complete (not a bare -s test): a promoted partial sweep must be
# re-attempted once the tunnel recovers (advisor finding, round 2)
if have_complete scale; then echo "done already"
elif healthy; then
    # 1500s/attempt caps the live TPU sweep; the 4600s budget leaves room
    # for probe + salvage (CPU fallback is disabled in watcher mode above)
    BENCH_BUDGET=4600 BENCH_TIMEOUT=1500 timeout 4800 python bench.py --scale \
        > runs/scale.new 2> runs/bench_scale_tpu.log
    promote scale
else echo "SKIP: tunnel unhealthy"; fi

echo "=== E. KdV soliton (N_f=20k, third-order fused engine, 10k+10k) ==="
# kdv.py's success line is "KdV soliton relative L2: ..." — NOT "Error u"
# (round-3 audit: the old marker never matched, so the step re-ran every
# watcher pass)
if done_marker runs/kdv_full_tpu.log "relative L2"; then echo "done already"
elif healthy; then
    TDQ_CKPT=runs/ck_kdv timeout 5400 python examples/kdv.py > runs/kdv_full_tpu.log 2>&1
    grep -a "relative L2" runs/kdv_full_tpu.log || tail -3 runs/kdv_full_tpu.log
else echo "SKIP: tunnel unhealthy"; fi

echo "=== F. 2D Burgers (N_f=20k 3-D domain, 1k+1k) ==="
# burgers2d has no analytic truth (like the reference's testing.py): its
# success line is "final loss: ..." — the old "Error u" marker never
# matched (round-3 audit)
if done_marker runs/burgers2d_full_tpu.log "final loss"; then echo "done already"
elif healthy; then
    TDQ_CKPT=runs/ck_burgers2d timeout 3600 python examples/burgers2d.py > runs/burgers2d_full_tpu.log 2>&1
    grep -a "final loss" runs/burgers2d_full_tpu.log || tail -3 runs/burgers2d_full_tpu.log
else echo "SKIP: tunnel unhealthy"; fi

echo "=== I. Nonlinear Schrödinger (2-output system, N_f=20k, 10k+10k) ==="
if done_marker runs/schrodinger_full_tpu.log "Error u"; then echo "done already"
elif healthy; then
    TDQ_CKPT=runs/ck_schrodinger timeout 5400 python examples/schrodinger.py > runs/schrodinger_full_tpu.log 2>&1
    grep -a "Error u" runs/schrodinger_full_tpu.log || tail -3 runs/schrodinger_full_tpu.log
else echo "SKIP: tunnel unhealthy"; fi

echo "=== H. AC-SA with the exactly-periodic embedding net (beyond-reference) ==="
# same flagship config as ac_sa.py --periodic-net, driven by the
# north-star scheduler (eager refinement fallback, resume, time-to-target
# timeline) chasing the driver metric's literal bar rel-L2 <= 1e-3 —
# plausible for this ansatz (7.7e-3 at one-fifth size on CPU) where plain
# SA-PINN publishes 2.1e-2.  Generic residual engine (embedding nets
# bypass the MLP-only fused path) — fine on-chip, hours on CPU, hence
# TPU-gated.  Self-promotes to BENCH_TPU_northstar_periodic.json.
if [ -s BENCH_TPU_northstar_periodic.json ] \
        && grep -qE '"status": "(complete|exhausted)"' \
            BENCH_TPU_northstar_periodic.json; then
    echo "done already (terminal)"
elif healthy; then
    NS_ARM=periodic NS_BUDGET=2000 timeout 2600 python scripts/tpu_northstar.py \
        >> runs/ac_sa_periodic_tpu.log 2>&1
    tail -2 runs/ac_sa_periodic_tpu.log
else echo "SKIP: tunnel unhealthy"; fi

echo "=== G. resampling ablation (Burgers, fixed vs adaptive draw) ==="
if done_marker runs/resample_ablation_tpu.log "improvement"; then echo "done already"
elif healthy; then
    timeout 2400 python scripts/resample_ablation.py --seeds 3 \
        > runs/resample_ablation_tpu.log 2>&1
    tail -2 runs/resample_ablation_tpu.log
else echo "SKIP: tunnel unhealthy"; fi

echo "ALL EXTRA CONVERGENCE RUNS DONE"
