# Container parity with the reference's Dockerfile (/root/reference/
# Dockerfile:1), retargeted from CUDA/TF to the JAX TPU stack: on a Cloud
# TPU VM the libtpu runtime is provided by the `jax[tpu]` extra.
FROM python:3.12-slim

# g++ builds the native ESE sampler lazily on first use
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/tensordiffeq-tpu
COPY pyproject.toml README.md ./
COPY tensordiffeq_tpu ./tensordiffeq_tpu

# CPU wheels by default; on a TPU VM build with:
#   --build-arg JAX_EXTRA="jax[tpu] -f https://storage.googleapis.com/jax-releases/libtpu_releases.html"
ARG JAX_EXTRA="jax"
RUN pip install --no-cache-dir ${JAX_EXTRA} && \
    pip install --no-cache-dir ".[all]"

CMD ["python", "-c", "import tensordiffeq_tpu as tdq; print(tdq.__doc__.splitlines()[0])"]
