#!/usr/bin/env python
"""Benchmark harness (driver contract: print ONE JSON line to stdout).

Default mode measures the headline config of the reference — Allen-Cahn
Self-Adaptive PINN, N_f=50,000 collocation points, 2-128-128-128-128-1 tanh
MLP, per-point residual λ + per-point IC λ (reference ``examples/AC-SA.py``)
— as *training throughput in collocation-points/sec/chip*: full SA minimax
Adam steps (loss + grads over params and λ + dual Adam update) timed on the
default JAX backend.

``vs_baseline`` is the ratio to a reference-style TensorFlow-2 train step
(same network, same residual via nested GradientTape, same dual-Adam SA
update, ``tf.function``-compiled) measured on the same host.  The reference
framework has no TPU path — TF-on-this-host is what it can actually deliver
here.  If TF is unavailable the last same-host TF measurement recorded in
``BENCH_BASELINE_CACHE.json`` is used.

``--full`` instead trains AC-SA for real (Adam + L-BFGS) and reports
time-to-L2<2.1e-2 (the SA-PINN paper's reported accuracy, cited at reference
``models.py:37``) against the spectral solution from
:mod:`tensordiffeq_tpu.exact`.

Env knobs: ``BENCH_NF`` (default 50000), ``BENCH_STEPS`` (default 100),
``BENCH_FAST=1`` (tiny smoke config).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
CACHE = os.path.join(REPO, "BENCH_BASELINE_CACHE.json")

EPS = 0.0001  # Allen-Cahn diffusion coefficient


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------- #
# JAX (ours)
# --------------------------------------------------------------------------- #
def build_solver(n_f, nx, nt, widths, seed=0, fused=None):
    import tensordiffeq_tpu as tdq
    from tensordiffeq_tpu import IC, CollocationSolverND, DomainND, grad, periodicBC

    domain = DomainND(["x", "t"], time_var="t")
    domain.add("x", [-1.0, 1.0], nx)
    domain.add("t", [0.0, 1.0], nt)
    domain.generate_collocation_points(n_f, seed=seed)

    def func_ic(x):
        return x ** 2 * np.cos(np.pi * x)

    def deriv_model(u, x, t):
        return u(x, t), grad(u, "x")(x, t)

    bcs = [IC(domain, [func_ic], var=[["x"]]),
           periodicBC(domain, ["x"], [deriv_model])]

    def f_model(u, x, t):
        u_xx = grad(grad(u, "x"), "x")
        u_t = grad(u, "t")
        uv = u(x, t)
        return u_t(x, t) - EPS * u_xx(x, t) + 5.0 * uv ** 3 - 5.0 * uv

    rng = np.random.RandomState(seed)
    solver = CollocationSolverND(verbose=False)
    solver.compile(
        [2, *widths, 1], f_model, domain, bcs, Adaptive_type=1,
        dict_adaptive={"residual": [True], "BCs": [True, False]},
        init_weights={"residual": [rng.rand(n_f, 1)],
                      "BCs": [100.0 * rng.rand(nx, 1), None]},
        fused=fused)
    return solver


def bench_jax_throughput(n_f, nx, nt, widths, n_steps):
    import jax
    import optax
    from tensordiffeq_tpu.training.fit import make_optimizer

    # autotune: measure generic vs fused residual engines at this exact
    # config and keep the faster one for the headline number
    solver = build_solver(n_f, nx, nt, widths, fused="autotune")
    opt = make_optimizer()

    def train_step(trainables, opt_state, X):
        def loss_over(tr):
            return solver.loss_fn(tr["params"], tr["lambdas"]["BCs"],
                                  tr["lambdas"]["residual"], X)
        (total, _), grads = jax.value_and_grad(loss_over, has_aux=True)(trainables)
        updates, opt_state = opt.update(grads, opt_state, trainables)
        return optax.apply_updates(trainables, updates), opt_state, total

    trainables = {"params": solver.params, "lambdas": solver.lambdas}
    opt_state = opt.init(trainables)
    step = jax.jit(train_step, donate_argnums=(0, 1))

    t0 = time.time()
    trainables, opt_state, loss = step(trainables, opt_state, solver.X_f)
    jax.block_until_ready(loss)
    log(f"[jax] compile+first step: {time.time() - t0:.1f}s "
        f"(backend={jax.default_backend()}, {len(jax.devices())} device(s))")

    t0 = time.time()
    for _ in range(n_steps):
        trainables, opt_state, loss = step(trainables, opt_state, solver.X_f)
    jax.block_until_ready(loss)
    dt = time.time() - t0
    n_chips = max(1, len(jax.devices())) if jax.default_backend() != "cpu" else 1
    pts = n_f * n_steps / dt / n_chips
    log(f"[jax] {n_steps} SA steps in {dt:.2f}s -> {pts:,.0f} pts/sec/chip "
        f"(loss={float(loss):.4f})")
    return pts


# --------------------------------------------------------------------------- #
# TF2 reference-style baseline
# --------------------------------------------------------------------------- #
def bench_tf_baseline(n_f, nx, widths, n_steps):
    """Reference-style SA train step (networks.py MLP + nested-tape residual +
    dual-Adam minimax of fit.py:125-145), tf.function-compiled, same host."""
    import tensorflow as tf

    tf.random.set_seed(0)
    rng = np.random.RandomState(0)
    X = tf.constant(
        (rng.rand(n_f, 2) * [2.0, 1.0] - [1.0, 0.0]).astype(np.float32))
    x_f, t_f = X[:, 0:1], X[:, 1:2]
    x0 = np.linspace(-1, 1, nx).astype(np.float32).reshape(-1, 1)
    X0 = tf.constant(np.hstack([x0, np.zeros_like(x0)]))
    u0 = tf.constant((x0 ** 2 * np.cos(np.pi * x0)).astype(np.float32))

    layers = [tf.keras.layers.Input((2,))]
    for w in widths:
        layers.append(tf.keras.layers.Dense(
            w, activation="tanh", kernel_initializer="glorot_normal"))
    layers.append(tf.keras.layers.Dense(1, activation=None))
    model = tf.keras.Sequential(layers)

    lam_res = tf.Variable(rng.rand(n_f, 1).astype(np.float32))
    lam_ic = tf.Variable(100.0 * rng.rand(nx, 1).astype(np.float32))
    opt_net = tf.keras.optimizers.Adam(0.005, beta_1=0.99)
    opt_lam = tf.keras.optimizers.Adam(0.005, beta_1=0.99)

    @tf.function
    def train_step():
        with tf.GradientTape() as outer:
            with tf.GradientTape(persistent=True) as t2:
                t2.watch([x_f, t_f])
                with tf.GradientTape(persistent=True) as t1:
                    t1.watch([x_f, t_f])
                    u = model(tf.concat([x_f, t_f], 1))
                u_x = t1.gradient(u, x_f)
                u_t = t1.gradient(u, t_f)
            u_xx = t2.gradient(u_x, x_f)
            f_u = u_t - EPS * u_xx + 5.0 * u ** 3 - 5.0 * u
            loss_res = tf.reduce_mean((lam_res * f_u) ** 2)
            u0_pred = model(X0)
            loss_ic = tf.reduce_mean((lam_ic * (u0_pred - u0)) ** 2)
            loss = loss_res + loss_ic
        grads = outer.gradient(loss, model.trainable_variables + [lam_res, lam_ic])
        opt_net.apply_gradients(zip(grads[:-2], model.trainable_variables))
        opt_lam.apply_gradients([(-grads[-2], lam_res), (-grads[-1], lam_ic)])
        return loss

    t0 = time.time()
    train_step()
    log(f"[tf] trace+first step: {time.time() - t0:.1f}s")
    t0 = time.time()
    for _ in range(n_steps):
        loss = train_step()
    _ = float(loss)
    dt = time.time() - t0
    pts = n_f * n_steps / dt
    log(f"[tf] {n_steps} SA steps in {dt:.2f}s -> {pts:,.0f} pts/sec "
        f"(loss={float(loss):.4f})")
    return pts


def get_baseline(n_f, nx, widths, n_steps):
    key = f"tf_sa_pts_per_sec_nf{n_f}"
    try:
        pts = bench_tf_baseline(n_f, nx, widths, n_steps)
        try:
            cache = json.load(open(CACHE)) if os.path.exists(CACHE) else {}
            # Keep the best baseline seen: a loaded host under-measures TF,
            # which would inflate vs_baseline for later TF-less runs.
            cache[key] = max(pts, cache.get(key, 0.0))
            json.dump(cache, open(CACHE, "w"), indent=1)
        except OSError:
            pass
        return pts
    except Exception as e:  # TF missing or broken: use cached measurement
        log(f"[tf] baseline unavailable ({type(e).__name__}: {e}); "
            "falling back to cached measurement")
        if os.path.exists(CACHE):
            cache = json.load(open(CACHE))
            if key in cache:
                return cache[key]
        return None


# --------------------------------------------------------------------------- #
# --engines: residual-engine comparison (generic autodiff vs fused Taylor vs
# pallas VMEM kernel) on the same SA train step
# --------------------------------------------------------------------------- #
def bench_engines(n_f, nx, nt, widths, n_steps):
    import jax
    import optax
    from tensordiffeq_tpu.training.fit import make_optimizer

    results = {}
    for engine, fused in [("generic", False), ("fused-xla", True),
                          ("fused-pallas", "pallas")]:
        solver = build_solver(n_f, nx, nt, widths, fused=fused)
        opt = make_optimizer()

        def train_step(trainables, opt_state, X, solver=solver, opt=opt):
            def loss_over(tr):
                return solver.loss_fn(tr["params"], tr["lambdas"]["BCs"],
                                      tr["lambdas"]["residual"], X)
            (total, _), grads = jax.value_and_grad(
                loss_over, has_aux=True)(trainables)
            updates, opt_state = opt.update(grads, opt_state, trainables)
            return optax.apply_updates(trainables, updates), opt_state, total

        trainables = {"params": solver.params, "lambdas": solver.lambdas}
        opt_state = opt.init(trainables)
        step = jax.jit(train_step, donate_argnums=(0, 1))
        t0 = time.time()
        trainables, opt_state, loss = step(trainables, opt_state, solver.X_f)
        jax.block_until_ready(loss)
        compile_t = time.time() - t0
        t0 = time.time()
        for _ in range(n_steps):
            trainables, opt_state, loss = step(trainables, opt_state,
                                               solver.X_f)
        jax.block_until_ready(loss)
        dt = time.time() - t0
        pts = n_f * n_steps / dt
        results[engine] = pts
        log(f"[engines] {engine}: compile {compile_t:.1f}s, "
            f"{pts:,.0f} pts/sec (loss={float(loss):.4f})")
    return results


# --------------------------------------------------------------------------- #
# --full: real training, time-to-L2
# --------------------------------------------------------------------------- #
def bench_time_to_l2(n_f, nx, nt, widths, target=2.1e-2,
                     adam_iter=10_000, newton_iter=10_000):
    from tensordiffeq_tpu.exact import allen_cahn_solution
    from tensordiffeq_tpu.helpers import find_L2_error

    xg, tg, usol = allen_cahn_solution()
    Xg = np.stack(np.meshgrid(xg, tg, indexing="ij"), -1).reshape(-1, 2)
    u_star = usol.reshape(-1, 1)

    solver = build_solver(n_f, nx, nt, widths)
    t0 = time.time()
    solver.fit(tf_iter=adam_iter, newton_iter=newton_iter)
    wall = time.time() - t0
    u_pred, _ = solver.predict(Xg, best_model=True)
    l2 = find_L2_error(u_pred, u_star)
    log(f"[full] wall={wall:.1f}s rel-L2={l2:.3e} (target {target:g})")
    return wall, float(l2)


# --------------------------------------------------------------------------- #
def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="train AC-SA to convergence and report time-to-L2")
    ap.add_argument("--engines", action="store_true",
                    help="compare generic / fused-xla / fused-pallas "
                         "residual engines on the SA train step")
    args = ap.parse_args()

    fast = os.environ.get("BENCH_FAST") == "1"
    n_f = int(os.environ.get("BENCH_NF", 2048 if fast else 50_000))
    n_steps = int(os.environ.get("BENCH_STEPS", 10 if fast else 100))
    nx, nt = (64, 16) if fast else (512, 201)
    widths = [32, 32] if fast else [128, 128, 128, 128]

    if args.engines:
        results = bench_engines(n_f, nx, nt, widths, n_steps)
        best = max(results, key=results.get)
        print(json.dumps({
            "metric": f"AC-SA step throughput by engine (best: {best})",
            "value": round(results[best]),
            "unit": "collocation-pts/sec/chip",
            "vs_baseline": round(results[best] / results["generic"], 3),
        }))
        return

    if args.full:
        wall, l2 = bench_time_to_l2(n_f, nx, nt, widths,
                                    adam_iter=100 if fast else 10_000,
                                    newton_iter=100 if fast else 10_000)
        print(json.dumps({
            "metric": "AC-SA wall-clock to rel-L2 (10k Adam + 10k L-BFGS)",
            "value": round(wall, 2), "unit": "s",
            "vs_baseline": l2,  # achieved rel-L2 recorded alongside
        }))
        return

    ours = bench_jax_throughput(n_f, nx, nt, widths, n_steps)
    base = get_baseline(n_f, nx, widths, max(3, n_steps // 10))
    vs = round(ours / base, 3) if base else 1.0
    print(json.dumps({
        "metric": "AC SA-PINN training throughput (full minimax step)",
        "value": round(ours), "unit": "collocation-pts/sec/chip",
        "vs_baseline": vs,
    }))


if __name__ == "__main__":
    main()
